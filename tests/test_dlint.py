"""Tests for the dlint static-analysis gate (tools/dlint/).

Fixture-driven: every rule gets positive snippets (must flag) and negative
snippets (must stay silent), run through the in-memory ``lint_source`` API
so nothing touches the repo tree. The final test runs the real gate over
the whole repo against the committed baseline — the "zero non-baselined
findings" invariant CI enforces via ``make lint-strict``.

The old tools/lint.py had no tests at all; these also cover the ported
F401/F811 rules, the suppression syntax, and the baseline workflow.
"""

from __future__ import annotations

import textwrap

import pytest

from tools.dlint import Baseline, BaselineEntry, REPO, RULES, lint_source, run


def findings_for(code, relpath, src):
    """Run one rule over a dedented snippet; return its findings."""
    return [
        f
        for f in lint_source(relpath, textwrap.dedent(src), select=[code])
        if f.code == code
    ]


# --------------------------------------------------------------------------
# registry basics


def test_registry_has_all_rule_codes():
    expected = {
        "DLP001", "DLP002", "DLP010", "DLP011",
        "DLP012", "DLP013", "DLP014", "DLP015", "DLP016", "DLP017",
        "DLP018", "DLP019", "DLP020", "DLP021",
    }
    assert expected <= set(RULES)
    for code, rule in RULES.items():
        assert rule.code == code
        assert rule.name and rule.rationale
    # The whole-program concurrency family lives in its own registry
    # (rules take a ProjectContext, not a FileContext) but shares the
    # code namespace: no overlap, and importing the project module is
    # enough to populate it (the CLI validates --select against both).
    from tools.dlint.project import PROJECT_RULES

    assert {"DLP030", "DLP031", "DLP032", "DLP033", "DLP034"} <= set(
        PROJECT_RULES
    )
    assert not set(RULES) & set(PROJECT_RULES)
    for code, rule in PROJECT_RULES.items():
        assert rule.code == code
        assert rule.name and rule.rationale


def test_syntax_error_reported_as_dlp000():
    out = lint_source("distilp_tpu/broken.py", "def f(:\n")
    assert [f.code for f in out] == ["DLP000"]


# --------------------------------------------------------------------------
# DLP001 / DLP002 — the ported F401/F811 checks


def test_unused_import_flagged():
    out = findings_for("DLP001", "distilp_tpu/x.py", """\
        import os
        import json

        print(json.dumps({}))
        """)
    assert len(out) == 1
    assert out[0].line == 1 and "`os`" in out[0].message


def test_dunder_all_reexport_counts_as_used():
    out = findings_for("DLP001", "distilp_tpu/x.py", """\
        from .core import thing

        __all__ = ["thing"]
        """)
    assert out == []


def test_function_scope_import_not_flagged():
    out = findings_for("DLP001", "distilp_tpu/x.py", """\
        def f():
            import jax
            return jax
        """)
    assert out == []


def test_import_redefinition_flagged():
    out = findings_for("DLP002", "distilp_tpu/x.py", """\
        import json
        import json

        print(json)
        """)
    assert len(out) == 1 and out[0].line == 2


# --------------------------------------------------------------------------
# DLP010 — x64 config placement


def test_x64_outside_sanctioned_module_flagged():
    out = findings_for("DLP010", "distilp_tpu/sched/scheduler.py", """\
        import jax

        jax.config.update("jax_enable_x64", True)
        """)
    assert len(out) == 1
    assert "outside the sanctioned modules" in out[0].message


def test_x64_after_jnp_import_flagged_even_in_sanctioned_module():
    out = findings_for("DLP010", "distilp_tpu/ops/ipm.py", """\
        import jax
        import jax.numpy as jnp

        jax.config.update("jax_enable_x64", True)
        x = jnp.zeros(3)
        """)
    assert len(out) == 1
    assert "AFTER jax.numpy" in out[0].message


def test_x64_before_jnp_import_in_sanctioned_module_ok():
    out = findings_for("DLP010", "distilp_tpu/ops/ipm.py", """\
        import jax

        jax.config.update("jax_enable_x64", True)

        import jax.numpy as jnp

        x = jnp.zeros(3)
        """)
    assert out == []


def test_x64_placement_exempt_in_tests_but_ordering_still_checked():
    ok = findings_for("DLP010", "tests/test_something.py", """\
        import jax

        jax.config.update("jax_enable_x64", True)

        import jax.numpy as jnp

        x = jnp.zeros(3)
        """)
    assert ok == []
    bad = findings_for("DLP010", "tests/test_something.py", """\
        import jax
        import jax.numpy as jnp

        jax.config.update("jax_enable_x64", True)
        x = jnp.zeros(3)
        """)
    assert len(bad) == 1 and "AFTER jax.numpy" in bad[0].message


def test_other_config_updates_ignored():
    out = findings_for("DLP010", "distilp_tpu/anywhere.py", """\
        import jax

        jax.config.update("jax_platforms", "cpu")
        """)
    assert out == []


# --------------------------------------------------------------------------
# DLP011 — host syncs inside traced code


def test_float_inside_jitted_function_flagged():
    out = findings_for("DLP011", "distilp_tpu/x.py", """\
        import jax

        @jax.jit
        def f(x):
            return float(x) + 1.0
        """)
    assert len(out) == 1 and "`float()`" in out[0].message


def test_item_inside_partial_jit_flagged():
    out = findings_for("DLP011", "distilp_tpu/x.py", """\
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("n",))
        def f(x, n):
            return x.item()
        """)
    assert len(out) == 1 and ".item()" in out[0].message


def test_np_asarray_inside_scan_body_flagged():
    out = findings_for("DLP011", "distilp_tpu/x.py", """\
        import jax
        import numpy as np

        def solve(xs):
            def step(carry, x):
                return carry + np.asarray(x), None
            out, _ = jax.lax.scan(step, 0.0, xs)
            return out
        """)
    assert len(out) == 1 and "np.asarray" in out[0].message


def test_lambda_passed_to_while_loop_flagged():
    out = findings_for("DLP011", "distilp_tpu/x.py", """\
        import jax

        def run(x):
            return jax.lax.while_loop(
                lambda s: bool(s), lambda s: s - 1, x
            )
        """)
    assert len(out) == 1 and "`bool()`" in out[0].message


def test_vmapped_local_function_flagged():
    out = findings_for("DLP011", "distilp_tpu/x.py", """\
        import jax

        def solve(ys):
            def price(y):
                return int(y)
            return jax.vmap(price)(ys)
        """)
    assert len(out) == 1 and "`int()`" in out[0].message


def test_tree_map_callable_not_treated_as_traced():
    # jax.tree.map runs its function eagerly on host; float() there is the
    # idiomatic way to pull results off device.
    out = findings_for("DLP011", "distilp_tpu/x.py", """\
        import jax

        def to_host(leaf):
            return float(leaf)

        def fetch(tree):
            return jax.tree.map(to_host, tree)
        """)
    assert out == []


def test_name_collision_across_scopes_not_flagged():
    # Host-side `price` in solve_host shares a name with the vmapped
    # `price` in solve_dev; only the lexically-visible one is traced.
    out = findings_for("DLP011", "distilp_tpu/x.py", """\
        import jax

        def solve_host(y):
            def price(v):
                return float(v)
            return price(y)

        def solve_dev(ys):
            def price(v):
                return v * 2
            return jax.vmap(price)(ys)
        """)
    assert out == []


def test_nested_traced_scopes_yield_one_finding_per_violation():
    # A lambda handed to lax inside a @jit def is seen by both scopes;
    # the violation must still surface exactly once or a count=1 baseline
    # entry could never absorb it.
    out = findings_for("DLP011", "distilp_tpu/x.py", """\
        import jax

        @jax.jit
        def f(x):
            return jax.lax.while_loop(lambda s: bool(s), lambda s: s - 1, x)
        """)
    assert len(out) == 1


def test_host_sync_outside_traced_scope_ok():
    out = findings_for("DLP011", "distilp_tpu/x.py", """\
        import numpy as np

        def host_prep(k, W):
            return np.asarray([float(k)] * int(W))
        """)
    assert out == []


def test_constant_cast_and_jnp_asarray_inside_trace_ok():
    out = findings_for("DLP011", "distilp_tpu/x.py", """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            tiny = jnp.asarray(1e-30, x.dtype)
            return x * float("inf") + tiny
        """)
    assert out == []


# --------------------------------------------------------------------------
# DLP012 — bare asserts in library code


def test_assert_in_library_flagged():
    out = findings_for("DLP012", "distilp_tpu/solver/x.py", """\
        def decode(blob, off):
            assert off == blob.shape[0], "layout drift"
            return blob
        """)
    assert len(out) == 1 and out[0].line == 2


def test_assert_in_tests_and_tools_exempt():
    snippet = """\
        def check(x):
            assert x > 0
        """
    assert findings_for("DLP012", "tests/test_x.py", snippet) == []
    assert findings_for("DLP012", "tools/helper.py", snippet) == []


# --------------------------------------------------------------------------
# DLP013 — schema layers must lazy-import jax


def test_toplevel_jax_import_in_schema_layer_flagged():
    out = findings_for("DLP013", "distilp_tpu/common/types.py", """\
        import jax

        def f():
            return jax
        """)
    assert len(out) == 1 and "lazy" in out[0].message


def test_toplevel_jax_import_in_try_block_still_flagged():
    out = findings_for("DLP013", "distilp_tpu/profiler/datatypes.py", """\
        try:
            import jax.numpy as jnp
        except ImportError:
            jnp = None

        print(jnp)
        """)
    assert len(out) == 1


def test_lazy_and_type_checking_imports_ok():
    out = findings_for("DLP013", "distilp_tpu/common/loaders.py", """\
        from typing import TYPE_CHECKING

        if TYPE_CHECKING:
            import jax

        def f():
            import jax.numpy as jnp
            return jnp.zeros(3)
        """)
    assert out == []


def test_eager_jax_distilp_module_import_in_lazy_layer_flagged():
    # Importing a module that itself eagerly loads jax defeats the lazy
    # contract just like `import jax`.
    out = findings_for("DLP013", "distilp_tpu/common/schema.py", """\
        from distilp_tpu.solver import backend_jax

        print(backend_jax)
        """)
    assert len(out) == 1
    out2 = findings_for("DLP013", "distilp_tpu/sched/scheduler.py", """\
        from distilp_tpu.ops import ipm_solve_batch

        print(ipm_solve_batch)
        """)
    assert len(out2) == 1


def test_lazy_safe_distilp_imports_ok_in_lazy_layer():
    # distilp_tpu.solver's own __init__ is jax-free at import time; sched
    # importing its siblings and solver's lazy API must stay clean.
    out = findings_for("DLP013", "distilp_tpu/sched/scheduler.py", """\
        from .fleet import Fleet
        from distilp_tpu.solver import halda_solve

        print(Fleet, halda_solve)
        """)
    assert out == []


def test_compute_modules_may_import_jax_eagerly():
    out = findings_for("DLP013", "distilp_tpu/ops/ipm.py", """\
        import jax

        print(jax)
        """)
    assert out == []


# --------------------------------------------------------------------------
# DLP014 — unseeded legacy NumPy RNG


def test_legacy_np_random_flagged():
    out = findings_for("DLP014", "distilp_tpu/profiler/device.py", """\
        import numpy as np

        buf = np.random.randn(128)
        """)
    assert len(out) == 1 and "default_rng" in out[0].message


def test_np_random_seed_also_flagged():
    # The WHOLE legacy API is banned: seed() just pins global state any
    # import can silently consume.
    out = findings_for("DLP014", "distilp_tpu/x.py", """\
        import numpy as np

        np.random.seed(0)
        x = np.random.randn(4)
        """)
    assert len(out) == 2


def test_default_rng_ok():
    out = findings_for("DLP014", "distilp_tpu/sched/sim.py", """\
        import numpy as np

        rng = np.random.default_rng(11)
        buf = rng.standard_normal(128)
        """)
    assert out == []


# --------------------------------------------------------------------------
# DLP015 — entry points must route through axon_guard


def test_entry_point_importing_jax_without_guard_flagged():
    out = findings_for("DLP015", "tools/probe.py", """\
        import jax

        if __name__ == "__main__":
            print(jax.devices())
        """)
    assert len(out) == 1 and "axon_guard" in out[0].message


def test_cli_relative_backend_import_without_guard_flagged():
    out = findings_for("DLP015", "distilp_tpu/cli/new_cli.py", """\
        def main():
            from ..solver import halda_solve
            return halda_solve
        """)
    assert len(out) == 1


def test_entry_point_with_guard_ok():
    out = findings_for("DLP015", "distilp_tpu/cli/new_cli.py", """\
        def main():
            from ..axon_guard import force_cpu_if_env_requested

            force_cpu_if_env_requested()
            from ..solver import halda_solve
            return halda_solve
        """)
    assert out == []


def test_backend_prefix_matches_on_module_boundary_only():
    # distilp_tpu.scheduling must NOT match the distilp_tpu.sched prefix.
    out = findings_for("DLP015", "tools/report.py", """\
        from distilp_tpu.scheduling_report import summarize

        if __name__ == "__main__":
            summarize()
        """)
    assert out == []


def test_level_one_relative_import_resolved_from_own_package():
    # `from .device import probe` inside distilp_tpu/profiler/ resolves to
    # distilp_tpu.profiler.device (backend-touching), not distilp_tpu.device.
    out = findings_for("DLP015", "distilp_tpu/cli/probe_cli.py", """\
        def main():
            from .backend_probe import probe
            from distilp_tpu.profiler.device import profile
            return probe, profile
        """)
    assert len(out) == 1


def test_schema_only_entry_point_needs_no_guard():
    out = findings_for("DLP015", "tools/import_fixtures.py", """\
        from distilp_tpu.common import load_model_profile

        if __name__ == "__main__":
            load_model_profile("x.json")
        """)
    assert out == []


def test_guarded_library_module_without_guard_flagged():
    # solver/api.py is a LIBRARY module, not a process entry point — but
    # plain halda_solve users get no CLI shim to arm the axon guard for
    # them, so the guarded-library extension treats it like one
    # (VERDICT round-5 finding 2).
    out = findings_for("DLP015", "distilp_tpu/solver/api.py", """\
        def halda_solve():
            from .backend_jax import solve_sweep_jax
            return solve_sweep_jax
        """)
    assert len(out) == 1 and "axon_guard" in out[0].message


def test_guarded_library_module_with_guard_ok():
    out = findings_for("DLP015", "distilp_tpu/twin/api.py", """\
        from ..axon_guard import force_cpu_if_env_requested

        def robustness_report():
            force_cpu_if_env_requested()
            from .engine import run_monte_carlo
            return run_monte_carlo
        """)
    assert out == []


def test_unguarded_plain_library_module_not_flagged():
    # Non-entry, non-guarded library modules (internal solver plumbing)
    # stay out of DLP015's scope — only the user-facing dispatch modules
    # carry the guard obligation.
    out = findings_for("DLP015", "distilp_tpu/solver/moe.py", """\
        def build():
            from .backend_jax import solve_sweep_jax
            return solve_sweep_jax
        """)
    assert out == []


def test_twin_layer_is_backend_touching_for_entry_points():
    out = findings_for("DLP015", "distilp_tpu/cli/twin_cli.py", """\
        def main():
            from ..twin import robustness_report
            return robustness_report
        """)
    assert len(out) == 1


def test_gateway_layer_is_lazy_for_dlp013():
    out = findings_for("DLP013", "distilp_tpu/gateway/gateway2.py", """\
        import jax

        def f():
            return jax
        """)
    assert len(out) == 1
    out = findings_for("DLP013", "distilp_tpu/gateway/gateway2.py", """\
        def f():
            import jax

            return jax
        """)
    assert out == []


def test_twin_layer_is_lazy_for_dlp013():
    out = findings_for("DLP013", "distilp_tpu/twin/engine.py", """\
        import jax

        def f():
            return jax
        """)
    assert len(out) == 1
    out = findings_for("DLP013", "distilp_tpu/twin/engine.py", """\
        def f():
            import jax
            return jax
        """)
    assert out == []


# --------------------------------------------------------------------------
# DLP016 — fixed-length scans that factorize need a convergence gate


_SCAN_CHOLESKY = """\
    import jax
    import jax.numpy as jnp

    def kernel(A, b):
        def step(state, _):
            chol = jax.scipy.linalg.cho_factor(A, lower=True)
            return jax.scipy.linalg.cho_solve(chol, state), None

        out, _ = jax.lax.scan(step, b, None, length=30)
        return out
    """


def test_fixed_scan_with_cholesky_flagged_in_kernel_layers():
    out = findings_for("DLP016", "distilp_tpu/ops/newkernel.py", _SCAN_CHOLESKY)
    assert len(out) == 1 and "cho_factor" in out[0].message
    assert findings_for(
        "DLP016", "distilp_tpu/solver/newbackend.py", _SCAN_CHOLESKY
    )


def test_fixed_scan_with_cholesky_ignored_outside_kernel_layers():
    # The contract covers ops// and solver/ kernels; a profiler helper
    # doing a tiny fixed factorization loop is not the hot path.
    out = findings_for("DLP016", "distilp_tpu/profiler/calib.py", _SCAN_CHOLESKY)
    assert out == []


def test_fixed_scan_with_convergence_gate_comment_ok():
    out = findings_for("DLP016", "distilp_tpu/ops/newkernel.py", """\
        import jax

        def kernel(A, b, n_chunks):
            def step(state, _):
                chol = jax.scipy.linalg.cho_factor(A, lower=True)
                return jax.scipy.linalg.cho_solve(chol, state), None

            def body(carry):
                state, ci = carry
                # convergence gate: the outer while_loop stops this chunked
                # scan once every batch element is done
                state, _ = jax.lax.scan(step, state, None, length=4)
                return state, ci + 1

            return jax.lax.while_loop(lambda c: c[1] < n_chunks, body, (b, 0))
        """)
    assert out == []


def test_fixed_scan_lambda_body_and_disable():
    src = """\
        import jax

        def kernel(A, b):
            out, _ = jax.lax.scan(
                lambda s, _: (jax.scipy.linalg.cho_solve(
                    jax.scipy.linalg.cho_factor(A), s), None),
                b, None, length=10)
            return out
        """
    assert len(findings_for("DLP016", "distilp_tpu/ops/k.py", src)) == 1
    suppressed = src.replace(
        "out, _ = jax.lax.scan(",
        "out, _ = jax.lax.scan(  # dlint: disable=DLP016\n",
    )
    assert findings_for("DLP016", "distilp_tpu/ops/k.py", suppressed) == []


def test_fixed_scan_without_cholesky_ok():
    out = findings_for("DLP016", "distilp_tpu/solver/x.py", """\
        import jax

        def redistribute(vals, M):
            def body(state, _):
                return state + 1, None

            out, _ = jax.lax.scan(body, vals, None, length=M)
            return out
        """)
    assert out == []


def test_fixed_scan_matrix_free_operator_flagged():
    """The PDHG extension: a fixed-length scan whose step applies the
    operator (`A @ x`) is the same pay-for-converged-work pattern as a
    fixed Cholesky loop — no factorization call required to trip it."""
    src = """\
        import jax
        import jax.numpy as jnp

        def pdhg(A, b, x, y):
            def step(state, _):
                x, y = state
                x = jnp.clip(x - 0.1 * (A.T @ y), 0.0, 1.0)
                y = y + 0.1 * (b - A @ x)
                return (x, y), None

            out, _ = jax.lax.scan(step, (x, y), None, length=1000)
            return out
        """
    out = findings_for("DLP016", "distilp_tpu/ops/firstorder.py", src)
    assert len(out) == 1 and "matmul" in out[0].message
    gated = src.replace(
        "out, _ = jax.lax.scan(",
        "# convergence gate: chunk bounded by the enclosing while_loop\n"
        "    out, _ = jax.lax.scan(",
    )
    assert findings_for("DLP016", "distilp_tpu/ops/firstorder.py", gated) == []


def test_fixed_scan_heavy_helper_resolved_through_call():
    """Delegating the operator application to a local helper (ops/pdhg.py's
    ``T`` idiom) must not evade the rule: the name-level call graph is
    followed to a fixpoint."""
    out = findings_for("DLP016", "distilp_tpu/ops/firstorder.py", """\
        import jax
        import jax.numpy as jnp

        def kernel(A, b, z0):
            def T(x, y):
                return x - 0.1 * (A.T @ y), y + 0.1 * (b - A @ x)

            def halpern(x, y):
                return T(x, y)

            def step(state, _):
                return halpern(*state), None

            out, _ = jax.lax.scan(step, z0, None, length=500)
            return out
        """)
    assert len(out) == 1


def test_fixed_scan_vector_ops_stay_exempt():
    """Cheap per-step vector arithmetic (vdot, elementwise) is not the
    pattern: only factorizations and matrix-operator products gate."""
    out = findings_for("DLP016", "distilp_tpu/ops/firstorder.py", """\
        import jax
        import jax.numpy as jnp

        def accumulate(xs, w):
            def step(acc, x):
                return acc + jnp.vdot(w, x) * x, None

            out, _ = jax.lax.scan(step, xs[0], None, length=64)
            return out
        """)
    assert out == []


def test_host_sync_in_first_order_kernel_flagged():
    """DLP011 coverage over the pdhg kernel shape: a host-sync float() on
    the residual inside the traced solve is exactly the per-iteration
    device->host round trip a matrix-free engine cannot afford."""
    out = findings_for("DLP011", "distilp_tpu/ops/firstorder.py", """\
        import jax
        import jax.numpy as jnp
        from functools import partial

        @partial(jax.jit, static_argnames=("iters",))
        def solve(A, b, x, iters):
            res = jnp.max(jnp.abs(b - A @ x))
            if float(res) > 1e-6:
                x = x + 1.0
            return x
        """)
    assert len(out) == 1 and "float()" in out[0].message
    # The sound shape: return the residual and read it OUTSIDE the trace.
    ok = findings_for("DLP011", "distilp_tpu/ops/firstorder.py", """\
        import jax
        import jax.numpy as jnp

        def driver(A, b, x):
            solve = jax.jit(lambda x: (x, jnp.max(jnp.abs(b - A @ x))))
            x, res = solve(x)
            return x, float(res)
        """)
    assert ok == []


# --------------------------------------------------------------------------
# DLP017 — no silent except handlers in the scheduler service layer


def test_silent_except_in_sched_flagged():
    out = findings_for("DLP017", "distilp_tpu/sched/newpart.py", """\
        def tick(self):
            try:
                self.solve()
            except RuntimeError:
                pass
        """)
    assert len(out) == 1 and "metrics sink" in out[0].message


def test_except_recording_through_metrics_ok():
    out = findings_for("DLP017", "distilp_tpu/sched/newpart.py", """\
        def tick(self):
            try:
                self.solve()
            except RuntimeError:
                self.metrics.inc("tick_failed")
        """)
    assert out == []


def test_except_reraising_ok():
    out = findings_for("DLP017", "distilp_tpu/sched/newpart.py", """\
        def tick(self):
            try:
                self.solve()
            except RuntimeError as e:
                raise ValueError("bad tick") from e
        """)
    assert out == []


def test_except_delegating_to_quarantine_recorder_ok():
    out = findings_for("DLP017", "distilp_tpu/sched/scheduler2.py", """\
        def handle(self, event):
            try:
                self.fleet.apply(event)
            except ValueError as e:
                return self._quarantine(event, str(e))
        """)
    assert out == []


def test_silent_except_outside_sched_not_flagged():
    out = findings_for("DLP017", "distilp_tpu/solver/x.py", """\
        def f(self):
            try:
                self.solve()
            except RuntimeError:
                pass
        """)
    assert out == []


def test_silent_except_in_gateway_flagged():
    # The gateway tier inherits the observability contract: its worker
    # and HTTP layers must account every swallowed fault.
    out = findings_for("DLP017", "distilp_tpu/gateway/newpart.py", """\
        def run(self):
            try:
                self.fn()
            except RuntimeError:
                pass
        """)
    assert len(out) == 1 and "metrics sink" in out[0].message


# --------------------------------------------------------------------------
# DLP018 — no blocking calls inside async def bodies in the gateway


def test_time_sleep_in_async_gateway_flagged():
    out = findings_for("DLP018", "distilp_tpu/gateway/http2.py", """\
        import time

        async def handle(self, reader, writer):
            time.sleep(0.1)
        """)
    assert len(out) == 1 and "blocks the gateway event loop" in out[0].message


def test_bare_sleep_import_in_async_gateway_flagged():
    out = findings_for("DLP018", "distilp_tpu/gateway/http2.py", """\
        from time import sleep

        async def handle(self):
            sleep(1)
        """)
    assert len(out) == 1 and "time.sleep" in out[0].message


def test_subprocess_run_in_async_gateway_flagged():
    out = findings_for("DLP018", "distilp_tpu/gateway/ops.py", """\
        import subprocess

        async def deploy(self):
            subprocess.run(["restart"])
        """)
    assert len(out) == 1


def test_aliased_blocking_imports_in_async_gateway_flagged():
    # Both binding forms must resolve: `from subprocess import run` and a
    # module alias (`import time as t`) block exactly as hard as the
    # literal dotted spellings the rule names.
    out = findings_for("DLP018", "distilp_tpu/gateway/ops.py", """\
        from subprocess import run

        async def deploy(self):
            run(["restart"])
        """)
    assert len(out) == 1 and "subprocess.run" in out[0].message
    out = findings_for("DLP018", "distilp_tpu/gateway/ops.py", """\
        import time as t

        async def handle(self):
            t.sleep(1)
        """)
    assert len(out) == 1 and "time.sleep" in out[0].message


def test_sync_socket_accept_in_async_gateway_flagged():
    out = findings_for("DLP018", "distilp_tpu/gateway/listener.py", """\
        async def serve(self, sock):
            conn, addr = sock.accept()
            return conn
        """)
    assert len(out) == 1 and "accept" in out[0].message


def test_asyncio_sleep_in_async_gateway_ok():
    out = findings_for("DLP018", "distilp_tpu/gateway/http2.py", """\
        import asyncio

        async def handle(self):
            await asyncio.sleep(0.1)
        """)
    assert out == []


def test_blocking_in_sync_def_or_nested_closure_ok():
    # Sync defs block a worker thread, not the loop; nested closures are
    # the run_in_executor idiom and run off-loop too.
    out = findings_for("DLP018", "distilp_tpu/gateway/worker2.py", """\
        import time

        def drain(self):
            time.sleep(0.1)

        async def snapshot(self, loop):
            def _wait():
                time.sleep(0.5)
            await loop.run_in_executor(None, _wait)
        """)
    assert out == []


def test_blocking_async_outside_gateway_not_flagged():
    out = findings_for("DLP018", "distilp_tpu/sched/x.py", """\
        import time

        async def tick(self):
            time.sleep(0.1)
        """)
    assert out == []


# --------------------------------------------------------------------------
# the obs/ layer joins the serving-layer contracts (DLP013/017/018)


def test_obs_layer_joins_lazy_jax_contract():
    out = findings_for("DLP013", "distilp_tpu/obs/exporter.py", """\
        import jax

        def export(spans):
            return jax.numpy.asarray(spans)
        """)
    assert len(out) == 1 and "lazy" in out[0].message
    ok = findings_for("DLP013", "distilp_tpu/obs/exporter.py", """\
        def export(spans):
            import jax

            return jax.numpy.asarray(spans)
        """)
    assert ok == []


def test_obs_layer_joins_silent_except_contract():
    out = findings_for("DLP017", "distilp_tpu/obs/writer.py", """\
        def write(self, rec):
            try:
                self.fh.write(rec)
            except OSError:
                pass
        """)
    assert len(out) == 1 and "metrics sink" in out[0].message


def test_obs_layer_joins_blocking_async_contract():
    out = findings_for("DLP018", "distilp_tpu/obs/pusher.py", """\
        import time

        async def push(self):
            time.sleep(0.1)
        """)
    assert len(out) == 1 and "blocks the gateway event loop" in out[0].message


def test_traffic_layer_is_lazy_for_dlp013():
    # Generating or byte-checking an open-loop schedule must not pay
    # backend init: traffic/ is in the lazy set like gateway/ and obs/.
    out = findings_for("DLP013", "distilp_tpu/traffic/newgen.py", """\
        import jax

        def gen():
            return jax.numpy.zeros(3)
        """)
    assert len(out) == 1
    ok = findings_for("DLP013", "distilp_tpu/traffic/newgen.py", """\
        def gen():
            import jax

            return jax.numpy.zeros(3)
        """)
    assert ok == []


def test_traffic_layer_joins_silent_except_contract():
    # The traffic harness audits shed/coalesce accounting — a swallowed
    # exception there hides exactly what it exists to surface.
    out = findings_for("DLP017", "distilp_tpu/traffic/runner.py", """\
        def fire(self, gw, ev):
            try:
                gw.handle_event("f0", ev)
            except Exception:
                pass
        """)
    assert len(out) == 1 and "metrics sink" in out[0].message


def test_traffic_layer_joins_blocking_async_contract():
    # The open-loop dispatcher lives on the asyncio loop: one time.sleep
    # and every fleet's schedule slips together.
    out = findings_for("DLP018", "distilp_tpu/traffic/exec2.py", """\
        import time

        async def fire(self):
            time.sleep(0.1)
        """)
    assert len(out) == 1 and "blocks the gateway event loop" in out[0].message


def test_traffic_layer_joins_dlp019():
    out = findings_for("DLP019", "distilp_tpu/traffic/exec2.py", """\
        def note(self, m):
            m.inc("totally_novel_overload_counter")
        """)
    assert len(out) == 1


def test_admission_counters_registered_for_dlp019():
    # The shed/coalesce/degrade counters are registry entries (satellite
    # contract: a new admission counter cannot ship without HELP text).
    ok = findings_for("DLP019", "distilp_tpu/gateway/adm.py", """\
        def shed(self, near):
            self.metrics.inc("events_shed")
            self.metrics.inc("events_coalesced", 3)
            self.metrics.inc("spec_near_hit" if near else "spec_near_miss")
            self.metrics.inc("http_too_many_requests")
        """)
    assert ok == []
    bad = findings_for("DLP019", "distilp_tpu/gateway/adm.py", """\
        def shed(self):
            self.metrics.inc("events_shedded")
        """)
    assert len(bad) == 1 and "events_shedded" in bad[0].message


# --------------------------------------------------------------------------
# DLP019 — literal counter names must be registered in METRIC_REGISTRY


def test_unregistered_literal_counter_flagged():
    out = findings_for("DLP019", "distilp_tpu/sched/newpart.py", """\
        def tick(self):
            self.metrics.inc("totally_novel_counter")
        """)
    assert len(out) == 1
    assert "METRIC_REGISTRY" in out[0].message
    assert "totally_novel_counter" in out[0].message


def test_registered_literal_counter_ok():
    out = findings_for("DLP019", "distilp_tpu/sched/newpart.py", """\
        def tick(self):
            self.metrics.inc("events_total")
            self.metrics.inc("breaker_open")
        """)
    assert out == []


def test_conditional_literal_counter_checks_both_branches():
    # The `"pool_hit" if hit else "pool_miss"` idiom: both branches must
    # be registered; one rogue branch is one finding.
    ok = findings_for("DLP019", "distilp_tpu/sched/pool.py", """\
        def get(self, hit):
            self.metrics.inc("pool_hit" if hit else "pool_miss")
        """)
    assert ok == []
    bad = findings_for("DLP019", "distilp_tpu/sched/pool.py", """\
        def get(self, hit):
            self.metrics.inc("pool_hit" if hit else "rogue_branch")
        """)
    assert len(bad) == 1 and "rogue_branch" in bad[0].message


def test_dynamic_counter_names_not_checked_by_dlp019():
    # f-strings are covered by METRIC_FAMILIES (and the live-counter test
    # in tests/test_obs.py), not by the literal rule.
    out = findings_for("DLP019", "distilp_tpu/gateway/gw2.py", """\
        def note(self, worker_id):
            self.metrics.inc(f"worker_{worker_id}_events")
        """)
    assert out == []


def test_dlp019_scoped_to_serving_layers():
    # `.inc(` on arbitrary objects outside sched//gateway//obs/ (e.g. a
    # solver-side accumulator) is not this rule's business.
    out = findings_for("DLP019", "distilp_tpu/solver/acc.py", """\
        def bump(self):
            self.counts.inc("whatever_name")
        """)
    assert out == []
    out = findings_for("DLP019", "tests/test_something.py", """\
        def test_x(m):
            m.inc("whatever_name")
        """)
    assert out == []


def test_spec_counters_registered_for_dlp019():
    # The speculative-replanning counters are registry entries: literal
    # inc() sites across sched//gateway//obs pass, and a near-miss name
    # (e.g. a typo'd spec counter) still fails the gate.
    ok = findings_for("DLP019", "distilp_tpu/sched/speculate2.py", """\
        def probe(self, hit):
            self.metrics.inc("spec_hit" if hit else "spec_miss")
            self.metrics.inc("spec_presolve", 3)
            self.metrics.inc("spec_stale", 2)
            self.metrics.inc("spec_presolve_failed")
        """)
    assert ok == []
    bad = findings_for("DLP019", "distilp_tpu/sched/speculate2.py", """\
        def probe(self):
            self.metrics.inc("spec_hits")
        """)
    assert len(bad) == 1 and "spec_hits" in bad[0].message


def test_dlp019_obs_layer_in_scope():
    out = findings_for("DLP019", "distilp_tpu/obs/flight2.py", """\
        def dump(self):
            self.metrics.inc("unregistered_flight_counter")
        """)
    assert len(out) == 1


# --------------------------------------------------------------------------
# suppressions


def test_same_line_disable_suppresses():
    out = findings_for("DLP012", "distilp_tpu/x.py", """\
        def f(x):
            assert x  # dlint: disable=DLP012
        """)
    assert out == []


def test_disable_all_and_disable_file():
    src_all = """\
        def f(x):
            assert x  # dlint: disable=all
        """
    assert findings_for("DLP012", "distilp_tpu/x.py", src_all) == []
    src_file = """\
        # dlint: disable-file=DLP012

        def f(x):
            assert x

        def g(x):
            assert x
        """
    assert findings_for("DLP012", "distilp_tpu/x.py", src_file) == []


def test_disable_with_trailing_justification_still_suppresses():
    # README: "Suppress only with a reason the next reader can check" —
    # prose after the code list must not break the suppression.
    out = findings_for("DLP012", "distilp_tpu/x.py", """\
        def f(x):
            assert x  # dlint: disable=DLP012 layout is static here
        """)
    assert out == []


def test_directive_inside_string_literal_does_not_suppress():
    # Comments come from the tokenizer, not a line regex: directive-looking
    # text inside a string (a test fixture, a doc snippet) is data.
    out = findings_for("DLP012", "distilp_tpu/x.py", '''\
        SNIPPET = """
        # dlint: disable-file=DLP012
        """

        def f(x):
            assert x
        ''')
    assert len(out) == 1


def test_disable_of_other_code_does_not_suppress():
    out = findings_for("DLP012", "distilp_tpu/x.py", """\
        def f(x):
            assert x  # dlint: disable=DLP014
        """)
    assert len(out) == 1


# --------------------------------------------------------------------------
# baseline workflow


def _finding(path="distilp_tpu/a.py", code="DLP012", line=3):
    from tools.dlint import Finding

    return Finding(path, line, code, "msg")


def test_baseline_absorbs_up_to_count():
    bl = Baseline(entries=[BaselineEntry("distilp_tpu/a.py", "DLP012", 1, "ok")])
    new, old, stale = bl.partition([_finding(line=3), _finding(line=9)])
    assert len(old) == 1 and len(new) == 1 and stale == []


def test_baseline_stale_entry_detected():
    bl = Baseline(entries=[BaselineEntry("distilp_tpu/a.py", "DLP012", 2, "ok")])
    new, old, stale = bl.partition([_finding()])
    assert new == [] and len(old) == 1
    assert len(stale) == 1


def test_baseline_unjustified_entries_listed():
    bl = Baseline(
        entries=[
            BaselineEntry("a.py", "DLP012", 1, ""),
            BaselineEntry("b.py", "DLP014", 1, "justified"),
            BaselineEntry("c.py", "DLP014", 1, "TODO: justify or fix"),
        ]
    )
    # The --write-baseline placeholder counts as unjustified: strict mode
    # must keep failing until a human replaces it.
    assert [e.path for e in bl.unjustified()] == ["a.py", "c.py"]


def test_baseline_duplicate_entries_accumulate():
    # Two hand-written entries for the same (path, code) — e.g. distinct
    # reasons for two distinct asserts — must pool their counts, not
    # overwrite each other.
    bl = Baseline(
        entries=[
            BaselineEntry("distilp_tpu/a.py", "DLP012", 1, "first"),
            BaselineEntry("distilp_tpu/a.py", "DLP012", 1, "second"),
        ]
    )
    new, old, stale = bl.partition([_finding(line=3), _finding(line=9)])
    assert new == [] and len(old) == 2 and stale == []


def test_skip_dirs_matched_repo_relative_only(tmp_path):
    # A checkout living under .../build/... must not skip every file and
    # report a vacuously clean gate.
    from tools.dlint.core import iter_py_files

    root = tmp_path / "build" / "repo"
    root.mkdir(parents=True)
    (root / "mod.py").write_text("X = 1\n")
    (root / "__pycache__").mkdir()
    (root / "__pycache__" / "mod.py").write_text("X = 1\n")
    files = list(iter_py_files(root))
    assert [f.name for f in files] == ["mod.py"]
    assert "__pycache__" not in files[0].parts


def test_out_of_tree_path_does_not_crash(tmp_path):
    from tools.dlint import lint_paths

    p = tmp_path / "external.py"
    p.write_text("import numpy as np\nx = np.random.rand(3)\n")
    out = lint_paths([p], select=["DLP014"])
    assert len(out) == 1 and out[0].code == "DLP014"


def test_write_baseline_refuses_scope_or_reason_losing_combinations(capsys):
    from tools.dlint.__main__ import main

    # Subset runs would drop entries outside the subset; --no-baseline
    # would drop every human-written reason.
    assert main(["--write-baseline", "--select", "DLP012"]) == 2
    assert main(["--write-baseline", "tests"]) == 2
    assert main(["--write-baseline", "--no-baseline"]) == 2
    err = capsys.readouterr().err
    assert err.count("error:") == 3


def test_subset_run_does_not_report_unrelated_entries_stale(tmp_path):
    # `dlint --strict some/file.py` must not tell the user to trim
    # baseline entries whose findings live outside the scanned subset.
    p = tmp_path / "clean.py"
    p.write_text("X = 1\n")
    bl = Baseline(
        entries=[BaselineEntry("distilp_tpu/elsewhere.py", "DLP012", 1, "ok")]
    )
    result = run(paths=[p], baseline=bl)
    assert result.stale_entries == []


def test_baseline_roundtrip(tmp_path):
    p = tmp_path / "baseline.json"
    Baseline(
        entries=[BaselineEntry("a.py", "DLP012", 2, "grandfathered")]
    ).dump(p)
    loaded = Baseline.load(p)
    assert len(loaded.entries) == 1
    e = loaded.entries[0]
    assert (e.path, e.code, e.count, e.reason) == (
        "a.py", "DLP012", 2, "grandfathered",
    )


# --------------------------------------------------------------------------
# the repo-wide gate


@pytest.fixture(scope="module")
def repo_result():
    from tools.dlint import DEFAULT_BASELINE

    return run(baseline=Baseline.load(DEFAULT_BASELINE))


def test_repo_has_zero_non_baselined_findings(repo_result):
    msgs = [f.render() for f in repo_result.findings_new]
    assert msgs == [], "\n".join(msgs)


def test_repo_baseline_is_empty_or_justified(repo_result):
    assert repo_result.stale_entries == []
    assert repo_result.unjustified_entries == []


def test_repo_in_library_violations_stay_fixed():
    """The in-repo violations each JAX rule originally caught must not
    come back: the satellite fixes (backend_jax asserts -> ValueError,
    device.py seeded RNG) are what make the gate pass with an empty
    baseline."""
    lib = REPO / "distilp_tpu"
    from tools.dlint import lint_paths

    found = lint_paths(
        [lib],
        select=[
            "DLP010", "DLP011", "DLP012", "DLP013", "DLP014", "DLP015",
            "DLP016",
        ],
    )
    assert found == [], "\n".join(f.render() for f in found)


# --------------------------------------------------------------------------
# obs/convergence.py (solver-interior telemetry) joins the obs-layer
# contracts: lazy-jax (DLP013), accounted excepts (DLP017), registered
# metric names (DLP019) — fixture-pinned so the prefix coverage cannot
# silently regress out from under the new module.


def test_convergence_module_joins_lazy_jax_contract():
    out = findings_for("DLP013", "distilp_tpu/obs/convergence.py", """\
        import jax

        def decode(conv):
            return jax.numpy.asarray(conv["round_log"])
        """)
    assert len(out) == 1 and "lazy" in out[0].message
    # ...and importing an eager-jax distilp module is caught the same way
    out = findings_for("DLP013", "distilp_tpu/obs/convergence.py", """\
        from distilp_tpu.ops.ipm import TRACE_COLS
        """)
    assert len(out) == 1


def test_convergence_module_joins_silent_except_contract():
    out = findings_for("DLP017", "distilp_tpu/obs/convergence.py", """\
        def load(path):
            try:
                return open(path).read()
            except OSError:
                return None
        """)
    assert len(out) == 1 and "metrics sink" in out[0].message


def test_convergence_module_joins_metric_registry_contract():
    out = findings_for("DLP019", "distilp_tpu/obs/convergence.py", """\
        def tick(self):
            self.metrics.inc("conv_totally_unregistered")
        """)
    assert len(out) == 1 and "METRIC_REGISTRY" in out[0].message


def test_convergence_module_is_currently_clean():
    """The REAL obs/convergence.py passes its layer's contracts (no jax
    import, no silent excepts, no unregistered literal counters)."""
    from pathlib import Path

    src = Path("distilp_tpu/obs/convergence.py").read_text()
    for code in ("DLP013", "DLP017", "DLP019"):
        assert findings_for(code, "distilp_tpu/obs/convergence.py", src) == []


# --------------------------------------------------------------------------
# obs/timeline.py + obs/slo.py (the SLO engine) join the obs-layer
# contracts: lazy-jax (DLP013), accounted excepts (DLP017), registered
# metric names (DLP019) — fixture-pinned per module, like convergence.py,
# so the prefix coverage cannot silently regress out from under them.


def test_timeline_module_joins_lazy_jax_contract():
    out = findings_for("DLP013", "distilp_tpu/obs/timeline.py", """\
        import jax

        def sample(snapshot):
            return jax.numpy.asarray(snapshot)
        """)
    assert len(out) == 1 and "lazy" in out[0].message


def test_timeline_module_joins_silent_except_contract():
    # The exact failure mode the sampler must never have: a swallowed
    # sample error is an invisible observability outage.
    out = findings_for("DLP017", "distilp_tpu/obs/timeline.py", """\
        def sample_once(self):
            try:
                self.timeline.record_many(0.0, self._sample_fn())
            except Exception:
                return False
        """)
    assert len(out) == 1 and "metrics sink" in out[0].message


def test_timeline_module_joins_metric_registry_contract():
    out = findings_for("DLP019", "distilp_tpu/obs/timeline.py", """\
        def sample_once(self):
            self.metrics.inc("timeline_totally_unregistered")
        """)
    assert len(out) == 1 and "METRIC_REGISTRY" in out[0].message
    # The real counters ARE registered: the same fixture with the real
    # names passes.
    ok = findings_for("DLP019", "distilp_tpu/obs/timeline.py", """\
        def sample_once(self, ok):
            self.metrics.inc(
                "timeline_samples" if ok else "timeline_sample_error"
            )
        """)
    assert ok == []


def test_slo_module_joins_lazy_jax_contract():
    out = findings_for("DLP013", "distilp_tpu/obs/slo.py", """\
        from distilp_tpu.ops.pdhg import PDHG_AUTO_M
        """)
    assert len(out) == 1


def test_slo_module_joins_silent_except_contract():
    out = findings_for("DLP017", "distilp_tpu/obs/slo.py", """\
        def evaluate(self, now):
            try:
                return self._burns(now)
            except Exception:
                return []
        """)
    assert len(out) == 1


def test_slo_module_joins_metric_registry_contract():
    out = findings_for("DLP019", "distilp_tpu/obs/slo.py", """\
        def _transition(self, kind):
            self.metrics.inc("slo_alert_flapped")
        """)
    assert len(out) == 1 and "METRIC_REGISTRY" in out[0].message
    # Both branches of the real IfExp site resolve through the registry.
    ok = findings_for("DLP019", "distilp_tpu/obs/slo.py", """\
        def _transition(self, kind):
            self.metrics.inc(
                "slo_alert_opened" if kind == "open" else "slo_alert_closed"
            )
        """)
    assert ok == []


def test_slo_and_timeline_modules_are_currently_clean():
    """The REAL obs/slo.py + obs/timeline.py pass their layer's
    contracts (no jax import, no silent excepts, no unregistered
    literal counters)."""
    from pathlib import Path

    for mod in ("distilp_tpu/obs/slo.py", "distilp_tpu/obs/timeline.py"):
        src = Path(mod).read_text()
        for code in ("DLP013", "DLP017", "DLP019"):
            assert findings_for(code, mod, src) == [], (mod, code)


# --------------------------------------------------------------------------
# obs/memory.py (the memory ledger) joins the same obs-layer contracts:
# lazy-jax (DLP013), accounted excepts (DLP017), registered metric names
# (DLP019) — fixture-pinned per module so the prefix coverage cannot
# silently regress out from under it. ops/memmodel.py rides the repo-wide
# contracts (no bare asserts, guarded entry points don't apply).


def test_memory_module_joins_lazy_jax_contract():
    # The exact temptation this module must resist: live_array_bytes
    # wants jax at module level; the obs layer must stay importable
    # without a backend.
    out = findings_for("DLP013", "distilp_tpu/obs/memory.py", """\
        import jax

        def live_array_bytes():
            return sum(a.nbytes for a in jax.live_arrays())
        """)
    assert len(out) == 1 and "lazy" in out[0].message


def test_memory_module_joins_silent_except_contract():
    # A swallowed watermark failure is an invisible observability
    # outage — the same failure mode the sampler rule exists for.
    out = findings_for("DLP017", "distilp_tpu/obs/memory.py", """\
        def sample(self):
            try:
                return self._walk()
            except Exception:
                return None
        """)
    assert len(out) == 1 and "metrics sink" in out[0].message


def test_memory_module_joins_metric_registry_contract():
    out = findings_for("DLP019", "distilp_tpu/obs/memory.py", """\
        def _note(self):
            self.metrics.inc("mem_totally_unregistered")
        """)
    assert len(out) == 1 and "METRIC_REGISTRY" in out[0].message
    # The real counters ARE registered: the scheduler's watermark note
    # and the gateway's headroom-pressure note both resolve.
    ok = findings_for("DLP019", "distilp_tpu/obs/memory.py", """\
        def _note(self, pressure):
            self.metrics.inc("mem_pressure" if pressure else "mem_samples")
        """)
    assert ok == []


def test_memory_and_memmodel_modules_are_currently_clean():
    """The REAL obs/memory.py + ops/memmodel.py pass their layers'
    contracts (lazy jax, accounted-or-justified excepts, registered
    literal counters, no bare asserts)."""
    from pathlib import Path

    for mod in ("distilp_tpu/obs/memory.py", "distilp_tpu/ops/memmodel.py"):
        src = Path(mod).read_text()
        for code in ("DLP012", "DLP013", "DLP017", "DLP019"):
            assert findings_for(code, mod, src) == [], (mod, code)


# --------------------------------------------------------------------------
# DLP020 — jax.jit sites must be module-level + ledger-registered


def test_unregistered_module_level_jit_flagged():
    out = findings_for("DLP020", "distilp_tpu/ops/newkernel.py", """\
        import jax

        def impl(x):
            return x

        solve = jax.jit(impl, static_argnames=("n",))
        """)
    assert len(out) == 1 and "instrument" in out[0].message


def test_instrumented_module_level_jit_ok():
    out = findings_for("DLP020", "distilp_tpu/ops/newkernel.py", """\
        import jax
        from ..obs.compile_ledger import instrument

        def impl(x):
            return x

        solve = instrument(
            "ops.newkernel.solve",
            jax.jit(impl, static_argnames=("n",)),
            static_argnames=("n",),
        )
        """)
    assert out == []


def test_jit_decorated_def_flagged():
    out = findings_for("DLP020", "distilp_tpu/solver/newpath.py", """\
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("n",))
        def solve(x, n=1):
            return x

        @jax.jit
        def other(x):
            return x
        """)
    assert len(out) == 2
    assert all("instrument" in f.message for f in out)


def test_jit_inside_function_body_flagged():
    out = findings_for("DLP020", "distilp_tpu/twin/newengine.py", """\
        def build():
            import jax

            fn = jax.jit(lambda x: x)
            return fn
        """)
    assert len(out) == 1 and "function body" in out[0].message


def test_jit_inside_loop_body_flagged_as_storm():
    out = findings_for("DLP020", "distilp_tpu/sched/newtick.py", """\
        import jax

        def serve(items):
            for it in items:
                f = jax.jit(lambda x: x)
                f(it)
        """)
    assert len(out) == 1 and "loop body" in out[0].message


def test_lazy_kernel_cache_justified_disable_ok():
    """The twin idiom: a function-scope jit built ONCE into a module
    global carries a justified inline disable — the sanctioned shape."""
    out = findings_for("DLP020", "distilp_tpu/twin/newengine.py", """\
        _KERNEL = None

        def _build():
            global _KERNEL
            import jax
            from ..obs.compile_ledger import instrument

            _KERNEL = instrument(
                "twin.new_kernel",
                jax.jit(lambda x: x),  # dlint: disable=DLP020 built once into the module-global kernel cache
                static_argnames=(),
            )
            return _KERNEL
        """)
    assert out == []


def test_dlp020_out_of_scope_and_tests_exempt():
    snippet = """\
        import jax

        probe = jax.jit(lambda v: v * 1.0)
        """
    assert findings_for("DLP020", "distilp_tpu/profiler/device2.py", snippet) == []
    assert findings_for("DLP020", "tests/test_something.py", snippet) == []


# --------------------------------------------------------------------------
# DLP021 — hazards inside shard_map mesh bodies (host syncs + dense-A)


def test_host_sync_in_mesh_body_flagged():
    """DLP011's full call set re-fires as DLP021 inside a shard_map body
    — a gap DLP011 itself does not cover (shard_map is not in its
    consumer set), and in SPMD code the sync stalls every shard."""
    out = findings_for("DLP021", "distilp_tpu/ops/newmesh.py", """\
        import numpy as np
        import jax.numpy as jnp
        from ..utils import shardcompat

        def run(batch, mesh):
            def body(A_blk, b_blk):
                g = float(jnp.max(b_blk))
                k = int(b_blk.shape[0] * g)
                s = b_blk.sum().item()
                h = np.asarray(b_blk)
                return A_blk * (g + k + s) + h

            return shardcompat.shard_map(
                body, mesh, in_specs=None, out_specs=None
            )(batch.A, batch.b)
        """)
    assert len(out) == 4
    assert all("stalls every shard" in f.message for f in out)
    # ...and plain DLP011 stays silent here: shard_map bodies are DLP021's.
    assert findings_for("DLP011", "distilp_tpu/ops/newmesh.py", """\
        import jax.numpy as jnp
        from ..utils import shardcompat

        def run(batch, mesh):
            def body(b_blk):
                return float(jnp.max(b_blk))

            return shardcompat.shard_map(
                body, mesh, in_specs=None, out_specs=None
            )(batch.b)
        """) == []


def test_dense_a_materialization_in_mesh_body_flagged():
    out = findings_for("DLP021", "distilp_tpu/solver/newdispatch.py", """\
        import jax.numpy as jnp
        from ..utils import shardcompat

        def run(batch, mesh, B, m, n):
            def body(A_blk, b_blk):
                full = jnp.broadcast_to(A_blk, (B, m, n))
                z = jnp.zeros(shape=(B, m, n), dtype=A_blk.dtype)
                t = jnp.tile(A_blk, reps=(B, 1, 1))
                op = jnp.outer(b_blk, b_blk)
                return full + z + t + op.sum()

            return shardcompat.shard_map(
                body, mesh, in_specs=None, out_specs=None
            )(batch.A, batch.b)
        """)
    assert len(out) == 4
    assert sum("(B, m, n) dense operator" in f.message for f in out) == 3
    assert sum("per element" in f.message for f in out) == 1


def test_mesh_body_lambda_and_raw_shard_map_spelling():
    """Lambdas in the callable position count, under any shard_map
    spelling — the raw jax.experimental import, not just the shim."""
    out = findings_for("DLP021", "distilp_tpu/ops/newmesh.py", """\
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map

        def run(x, mesh):
            return shard_map(
                lambda b: b * float(jnp.max(b)),
                mesh, in_specs=None, out_specs=None,
            )(x)
        """)
    assert len(out) == 1 and "host sync" in out[0].message


def test_mesh_body_negatives_stay_silent():
    """Per-shard rank-2 blocks inside the body, rank-3 work OUTSIDE the
    body, out-of-scope layers, and tests all stay clean."""
    good = """\
        import jax
        import jax.numpy as jnp
        from ..utils import shardcompat

        def run(batch, mesh, B, m, n):
            pad = jnp.zeros((B, m, n), batch.A.dtype)  # host side: fine

            def body(A_blk, b_blk):
                blk = jnp.zeros((B, 4), b_blk.dtype)
                y = jax.vmap(lambda a, b: a @ b)(A_blk, b_blk + blk)
                return jax.lax.all_gather(y, "rows", axis=1, tiled=True)

            return shardcompat.shard_map(
                body, mesh, in_specs=None, out_specs=None
            )(pad, batch.b)
        """
    assert findings_for("DLP021", "distilp_tpu/ops/newmesh.py", good) == []
    bad = """\
        import jax.numpy as jnp
        from ..utils import shardcompat

        def run(x, mesh):
            return shardcompat.shard_map(
                lambda b: b * float(jnp.max(b)),
                mesh, in_specs=None, out_specs=None,
            )(x)
        """
    # Same hazard outside ops//solver/ (or in a test) is not this rule's.
    assert findings_for("DLP021", "distilp_tpu/profiler/topology2.py", bad) == []
    assert findings_for("DLP021", "tests/test_something.py", bad) == []


def test_dlp021_real_mesh_kernel_is_currently_clean():
    """The actual sharded kernel (ops/meshlp.py) passes its own gate:
    the shard_map body holds only per-shard blocks and collectives."""
    from pathlib import Path

    mod = "distilp_tpu/ops/meshlp.py"
    src = Path(mod).read_text()
    assert lint_source(mod, src, select=["DLP021"]) == [], mod


def test_dlp020_real_jit_modules_are_currently_clean():
    """Every in-scope module that actually jits passes: the entry points
    are instrument()-wrapped (ops/, solver/) or carry the one justified
    lazy-cache disable (twin/engine.py)."""
    from pathlib import Path

    for mod in (
        "distilp_tpu/ops/ipm.py",
        "distilp_tpu/ops/pdhg.py",
        "distilp_tpu/solver/backend_jax.py",
        "distilp_tpu/twin/engine.py",
    ):
        src = Path(mod).read_text()
        assert lint_source(mod, src, select=["DLP020"]) == [], mod


# --------------------------------------------------------------------------
# distilp_tpu/combine/ (the cross-shard solve combiner) joins the serving
# layers' contracts: lazy-jax (DLP013 — a BucketPolicy must build without
# backend init), accounted excepts (DLP017 — a swallowed flush failure
# strands every lane in the batch), registered metric names (DLP019), and
# registered jit entries (DLP020) — fixture-pinned so the prefix coverage
# cannot silently regress out from under the module.


def test_combine_module_joins_lazy_jax_contract():
    out = findings_for("DLP013", "distilp_tpu/combine/combiner.py", """\
        import jax

        def flush(blobs):
            return jax.numpy.stack(blobs)
        """)
    assert len(out) == 1 and "lazy" in out[0].message


def test_combine_module_joins_silent_except_contract():
    # The exact failure mode the combiner must never have: a batched
    # dispatch error swallowed on the flush thread leaves every
    # submitting shard blocked on a delivery that never comes.
    out = findings_for("DLP017", "distilp_tpu/combine/combiner.py", """\
        def flush(self, entries):
            try:
                return self.solve(entries)
            except Exception:
                return None
        """)
    assert len(out) == 1 and "metrics sink" in out[0].message


def test_combine_module_joins_metric_registry_contract():
    out = findings_for("DLP019", "distilp_tpu/combine/combiner.py", """\
        def flush(self):
            self.metrics.inc("combine_totally_unregistered")
        """)
    assert len(out) == 1 and "METRIC_REGISTRY" in out[0].message
    # The real counters ARE registered: the same sites with the real
    # names pass.
    ok = findings_for("DLP019", "distilp_tpu/combine/combiner.py", """\
        def flush(self, reason, n, waste, ms):
            self.metrics.inc("combine_batches")
            self.metrics.inc("combine_instances", n)
            self.metrics.inc(
                "combine_flush_full" if reason == "full"
                else "combine_flush_deadline"
            )
            self.metrics.inc("combine_dispatch_error")
            self.metrics.observe("combine_bucket_occupancy", float(n))
            self.metrics.observe("combine_padding_waste", waste)
            self.metrics.observe("combine_batch_ms", ms)
        """)
    assert ok == []


def test_combine_scheduler_counters_are_registered():
    """The scheduler-side combine counters (prepare/adopt path) pass
    DLP019 — every mode and failure shape of a combined tick has help
    text for the Prometheus exposition."""
    ok = findings_for("DLP019", "distilp_tpu/sched/newcombine.py", """\
        def adopt(self, stale):
            self.metrics.inc("combine_prepared")
            self.metrics.inc("combine_local")
            self.metrics.inc("combine_stale" if stale else "combine_fallback")
            self.metrics.inc("drift_tick_combine")
        """)
    assert ok == []


def test_combine_module_joins_jit_registry_contract():
    out = findings_for("DLP020", "distilp_tpu/combine/combiner.py", """\
        import jax

        def flush(self, batch):
            return jax.jit(self._solve)(batch)
        """)
    assert len(out) == 1


def test_combine_real_modules_are_currently_clean():
    """The REAL combine package passes all four contracts, and the
    batched entry point it dispatches through is instrument()-registered
    (not an '(unregistered)' compile in the ledger)."""
    from pathlib import Path

    for mod in (
        "distilp_tpu/combine/__init__.py",
        "distilp_tpu/combine/policy.py",
        "distilp_tpu/combine/combiner.py",
    ):
        src = Path(mod).read_text()
        for code in ("DLP013", "DLP017", "DLP019", "DLP020"):
            assert findings_for(code, mod, src) == [], (mod, code)
    src = Path("distilp_tpu/solver/backend_jax.py").read_text()
    assert 'instrument(\n    "solver._solve_batched"' in src


# --------------------------------------------------------------------------
# finding columns (PR 17 satellite: path:line:col rendering)


def test_finding_renders_with_and_without_column():
    from tools.dlint import Finding

    with_col = Finding("a.py", 3, "DLP012", "msg", col=5, end_col=9)
    assert with_col.render() == "a.py:3:5: DLP012 msg"
    without = Finding("a.py", 3, "DLP012", "msg")
    assert without.render() == "a.py:3: DLP012 msg"


def test_finding_at_converts_ast_offsets_to_one_based():
    import ast

    from tools.dlint.core import finding_at

    node = ast.parse("if a:\n    x = 1").body[0].body[0]  # col_offset 4
    f = finding_at("a.py", node, "DLP012", "msg")
    assert (f.line, f.col) == (2, 5)
    assert f.end_col is not None and f.end_col > f.col


def test_unused_import_points_at_the_exact_alias():
    # Multi-name imports: each finding's column lands on ITS name, not
    # column 1 of the statement.
    out = lint_source(
        "distilp_tpu/x.py", "import os, sys\n", select=["DLP001"]
    )
    assert [(f.line, f.col) for f in out] == [(1, 8), (1, 12)]
    assert out[0].render() == (
        "distilp_tpu/x.py:1:8: DLP001 `os` imported but unused (F401)"
    )


def test_columns_do_not_affect_baseline_matching():
    # Baseline entries key on (path, code) only: adding or refining
    # column info must never invalidate a committed baseline.
    from tools.dlint import Finding

    bl = Baseline(entries=[BaselineEntry("a.py", "DLP012", 1, "ok")])
    new, old, stale = bl.partition(
        [Finding("a.py", 3, "DLP012", "msg", col=7, end_col=12)]
    )
    assert new == [] and len(old) == 1 and stale == []


# --------------------------------------------------------------------------
# suppression edge cases (PR 17 satellite)


def test_disable_all_with_unrelated_disable_file_interplay():
    # `disable=all` silences every code on its line; a `disable-file` of a
    # DIFFERENT code elsewhere must not widen or narrow that: other lines
    # keep their findings.
    out = findings_for("DLP012", "distilp_tpu/x.py", """\
        # dlint: disable-file=DLP014

        def f(x):
            assert x  # dlint: disable=all

        def g(x):
            assert x
        """)
    assert len(out) == 1
    assert out[0].line == 7


def test_disable_file_with_trailing_prose_still_suppresses():
    out = findings_for("DLP012", "distilp_tpu/x.py", """\
        # dlint: disable-file=DLP012 invariant layout, see module docstring

        def f(x):
            assert x
        """)
    assert out == []


def test_disable_list_with_prose_suppresses_exactly_the_listed_codes():
    # The code list must stop at the first non-identifier: prose after the
    # list is a justification, not more codes.
    src = """\
        def f(x):
            assert x  # dlint: disable=DLP012,DLP014 checked by caller
        """
    assert findings_for("DLP012", "distilp_tpu/x.py", src) == []
    src_other = """\
        def f(x):
            assert x  # dlint: disable=DLP014 checked by caller
        """
    assert len(findings_for("DLP012", "distilp_tpu/x.py", src_other)) == 1


def test_all_stale_baseline_fails_strict_and_reports_every_entry():
    # A baseline whose every entry went stale (the findings were fixed)
    # passes a lax run but fails --strict, reporting ALL entries, not
    # just the first.
    from tools.dlint.core import RunResult

    bl = Baseline(
        entries=[
            BaselineEntry("distilp_tpu/a.py", "DLP012", 2, "old"),
            BaselineEntry("distilp_tpu/b.py", "DLP014", 1, "older"),
        ]
    )
    new, old, stale = bl.partition([])
    assert new == [] and old == [] and len(stale) == 2
    result = RunResult(
        findings_new=new,
        findings_baselined=old,
        stale_entries=stale,
        unjustified_entries=bl.unjustified(),
        n_files=1,
    )
    assert not result.failed(strict=False)
    assert result.failed(strict=True)


# --------------------------------------------------------------------------
# the whole-program concurrency family (DLP030-034)


def proj_findings(code, sources):
    """Run one project rule over in-memory modules keyed by relpath."""
    from tools.dlint.project import project_lint_sources

    return project_lint_sources(
        {rel: textwrap.dedent(src) for rel, src in sources.items()},
        select=[code],
    )


def test_dlp030_guarded_attr_access_without_lock_flagged():
    out = proj_findings("DLP030", {
        "distilp_tpu/gwx/box.py": """\
            import threading


            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}  # guarded-by: self._lock

                def good(self):
                    with self._lock:
                        self._items["a"] = 1

                def bad(self):
                    return self._items.get("a")
            """,
    })
    assert len(out) == 1
    assert "Box.bad" in out[0].message and "_items" in out[0].message


def test_dlp030_module_global_guard_and_init_exemption():
    out = proj_findings("DLP030", {
        "distilp_tpu/gwx/glob.py": """\
            import threading

            _LOCK = threading.Lock()
            _CACHE = {}  # guarded-by: _LOCK


            def good():
                with _LOCK:
                    _CACHE["k"] = 1


            def bad():
                _CACHE["k"] = 1
            """,
        "distilp_tpu/gwx/init_ok.py": """\
            import threading


            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._state = {}  # guarded-by: self._lock
                    self._state["seed"] = 1
            """,
    })
    assert len(out) == 1
    assert "`_CACHE`" in out[0].message and "bad" in out[0].message


def test_dlp030_infers_missing_annotation_from_locked_writes():
    # No annotation anywhere: written under the lock in one method, bare
    # in another -> the bare write is flagged as a seed for the contract.
    out = proj_findings("DLP030", {
        "distilp_tpu/gwx/seed.py": """\
            import threading


            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}

                def locked_write(self):
                    with self._lock:
                        self._items["a"] = 1

                def bare_write(self):
                    self._items["b"] = 2
            """,
    })
    assert len(out) == 1
    assert "guarded-by" in out[0].message


def test_dlp030_helper_called_only_under_lock_is_clean():
    # The combiner idiom: a private helper that mutates guarded state is
    # legal when EVERY resolved call site already holds the lock — the
    # entry-held pass propagates the held set into the helper.
    out = proj_findings("DLP030", {
        "distilp_tpu/gwx/helper.py": """\
            import threading


            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}  # guarded-by: self._lock

                def flush(self):
                    with self._lock:
                        self._drain()

                def also_flush(self):
                    with self._lock:
                        self._drain()

                def _drain(self):
                    self._items.clear()
            """,
    })
    assert out == []


def test_dlp031_blocking_under_lock_direct_and_interprocedural():
    out = proj_findings("DLP031", {
        "distilp_tpu/gwx/blk.py": """\
            import threading
            import time


            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad_direct(self):
                    with self._lock:
                        time.sleep(0.1)

                def _helper(self):
                    time.sleep(0.1)

                def bad_via_call(self):
                    with self._lock:
                        self._helper()

                def ok(self):
                    time.sleep(0.1)
                    with self._lock:
                        pass
            """,
    })
    assert len(out) == 2
    assert all("while holding" in f.message for f in out)
    assert any("_helper" in f.message for f in out)


def test_dlp031_condition_wait_on_innermost_lock_exempt():
    # Condition.wait RELEASES the lock it waits on; only waiting on a
    # condition while holding a DIFFERENT lock convoys that outer lock.
    out = proj_findings("DLP031", {
        "distilp_tpu/gwx/cv.py": """\
            import threading


            class Q:
                def __init__(self):
                    self._cv = threading.Condition()
                    self._outer = threading.Lock()

                def ok_wait(self):
                    with self._cv:
                        self._cv.wait()

                def bad_wait(self):
                    with self._outer:
                        with self._cv:
                            self._cv.wait()
            """,
    })
    assert len(out) == 1
    assert "releases" in out[0].message
    assert "_outer" in out[0].message  # the convoyed lock, not the cv
    assert out[0].line > 11  # the bad_wait site, not ok_wait


def test_dlp032_opposite_order_cycle_reported_with_witness_sites():
    out = proj_findings("DLP032", {
        "distilp_tpu/gwx/order.py": """\
            import threading

            A = threading.Lock()
            B = threading.Lock()


            def one():
                with A:
                    with B:
                        pass


            def two():
                with B:
                    with A:
                        pass
            """,
    })
    assert len(out) == 1
    assert "lock-order cycle" in out[0].message
    # Both directions of the cycle are named, with file:line witnesses.
    assert "gwx.order.A" in out[0].message
    assert "gwx.order.B" in out[0].message
    assert "distilp_tpu/gwx/order.py:" in out[0].message


def test_dlp032_consistent_order_is_clean():
    out = proj_findings("DLP032", {
        "distilp_tpu/gwx/order_ok.py": """\
            import threading

            A = threading.Lock()
            B = threading.Lock()


            def one():
                with A:
                    with B:
                        pass


            def two():
                with A:
                    with B:
                        pass
            """,
    })
    assert out == []


def test_dlp032_direct_reacquire_flagged_unless_rlock():
    src = """\
        import threading

        %s


        def f():
            with L:
                with L:
                    pass
        """
    bad = proj_findings(
        "DLP032",
        {"distilp_tpu/gwx/re.py": src % "L = threading.Lock()"},
    )
    assert len(bad) == 1 and "already held" in bad[0].message
    ok = proj_findings(
        "DLP032",
        {"distilp_tpu/gwx/re.py": src % "L = threading.RLock()"},
    )
    assert ok == []


def test_dlp033_sync_lock_and_blocking_in_async_def():
    out = proj_findings("DLP033", {
        "distilp_tpu/sched/aio.py": """\
            import threading
            import time

            _LOCK = threading.Lock()


            async def bad_lock():
                with _LOCK:
                    return 1


            async def bad_block():
                time.sleep(0.1)


            def sync_ok():
                with _LOCK:
                    time.sleep(0.1)  # dlint: disable=DLP031 fixture
            """,
    })
    assert len(out) == 2
    assert any("blocks the event loop" in f.message for f in out)
    assert any("stalls" in f.message for f in out)


def test_dlp033_thread_local_read_after_await():
    out = proj_findings("DLP033", {
        "distilp_tpu/sched/tls.py": """\
            import threading

            _TLS = threading.local()


            async def bad(other):
                await other()
                return _TLS.value


            async def ok(other):
                v = _TLS.value
                await other()
                return v
            """,
    })
    assert len(out) == 1
    assert "contextvars" in out[0].message
    assert "bad" in out[0].message


def test_dlp034_mutable_local_shared_with_thread_flagged():
    out = proj_findings("DLP034", {
        "distilp_tpu/gwx/esc.py": """\
            import threading


            def work(d):
                d["w"] = 1


            def bad_passed():
                shared = {}
                t = threading.Thread(target=work, args=(shared,))
                t.start()
                shared["k"] = 1
                return t


            def bad_captured():
                shared = {}

                def task():
                    shared["w"] = 1

                threading.Thread(target=task).start()
                return shared["k"]
            """,
    })
    assert len(out) == 2
    assert any("passed to" in f.message for f in out)
    assert any("captured by" in f.message for f in out)


def test_dlp034_ownership_transfer_and_locked_rendezvous_are_clean():
    out = proj_findings("DLP034", {
        "distilp_tpu/gwx/esc_ok.py": """\
            import threading

            _LOCK = threading.Lock()


            def work(d):
                d["w"] = 1


            def ok_handoff():
                payload = {}
                payload["k"] = 1
                threading.Thread(target=work, args=(payload,)).start()


            def ok_rendezvous():
                shared = {}
                threading.Thread(target=work, args=(shared,)).start()
                with _LOCK:
                    shared["k"] = 1
            """,
    })
    assert out == []


def test_dlp034_asyncio_task_sharing_is_not_an_escape():
    # create_task runs the coroutine on the SPAWNER's thread; container
    # sharing with it interleaves only at awaits (DLP033's territory).
    out = proj_findings("DLP034", {
        "distilp_tpu/gwx/aio_ok.py": """\
            import asyncio


            async def consume(d):
                d["c"] = 1


            async def ok():
                shared = {}
                asyncio.create_task(consume(shared))
                shared["k"] = 1
            """,
    })
    assert out == []


def test_dlp034_unguarded_mutable_global_passed_to_thread():
    src = """\
        import threading

        %s
        _BUF = []%s


        def work(b):
            b.append(1)


        def spawn():
            threading.Thread(target=work, args=(_BUF,)).start()
        """
    bad = proj_findings(
        "DLP034",
        {"distilp_tpu/gwx/gesc.py": src % ("", "")},
    )
    assert len(bad) == 1 and "mutable module global" in bad[0].message
    ok = proj_findings(
        "DLP034",
        {
            "distilp_tpu/gwx/gesc.py": src
            % ("_BUF_LOCK = threading.Lock()", "  # guarded-by: _BUF_LOCK")
        },
    )
    assert ok == []


def test_project_rule_findings_honor_suppression_comments():
    out = proj_findings("DLP030", {
        "distilp_tpu/gwx/supp.py": """\
            import threading


            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}  # guarded-by: self._lock

                def bad(self):
                    return self._items.get("a")  # dlint: disable=DLP030 snapshot read, staleness is fine here
            """,
    })
    assert out == []


# --------------------------------------------------------------------------
# --changed plumbing and the static/runtime lock-graph contract


def test_changed_files_returns_list_in_a_git_repo():
    from tools.dlint.__main__ import changed_files

    out = changed_files()
    assert out is not None
    assert all(str(p).endswith(".py") for p in out)


def test_empty_path_subset_runs_project_pass_only():
    # `--changed` with a clean tree: per-file rules see NO files (an
    # explicit empty subset must not fall back to the full walk), but
    # the whole-program pass still runs — cross-file findings caused by
    # a committed edit still surface.
    result = run(paths=[], baseline=Baseline(), with_project=True)
    assert result.n_files == -1
    for f in result.findings_new:
        assert f.code.startswith("DLP03"), f.render()


def test_static_lock_graph_covers_the_gateway_protocol():
    # The ground truth the runtime sanitizer validates against: batch
    # admission nests the worker submit lock (and the shed path nests
    # the flight ring / shed window / counters) under the admission
    # lock. If this shrinks, smoke-lockwatch's subset check goes blind.
    from tools.dlint.__main__ import _static_graph

    g = _static_graph()
    edges = {(e["from"], e["to"]) for e in g["edges"]}
    assert ("gateway.admission", "worker.submit") in edges
    assert ("gateway.admission", "flight.ring") in edges
    nodes = set(g["nodes"])
    assert {"gateway.admission", "worker.submit", "combiner.buckets"} <= nodes


def test_check_lockwatch_subset_empty_and_witness_verdicts(tmp_path, capsys):
    import json as _json

    from tools.dlint.__main__ import check_lockwatch

    p = tmp_path / "lw.json"

    def verdict(blob):
        p.write_text(_json.dumps(blob))
        rc = check_lockwatch(p)
        return rc, capsys.readouterr().out

    ok_rc, ok_out = verdict({
        "edges": [
            {"from": "gateway.admission", "to": "worker.submit", "count": 3}
        ],
        "witnesses": [],
    })
    assert ok_rc == 0 and "lockwatch ok" in ok_out

    rev_rc, rev_out = verdict({
        "edges": [
            {"from": "worker.submit", "to": "gateway.admission", "count": 1}
        ],
        "witnesses": [],
    })
    assert rev_rc == 1 and "missing from the static graph" in rev_out

    empty_rc, empty_out = verdict({"edges": [], "witnesses": []})
    assert empty_rc == 1 and "EMPTY" in empty_out

    wit_rc, wit_out = verdict({
        "edges": [
            {"from": "gateway.admission", "to": "worker.submit", "count": 1}
        ],
        "witnesses": [
            {"cycle": ["a", "b", "a"], "thread": "T1", "edge": ["b", "a"]}
        ],
    })
    assert wit_rc == 1 and "cycle witness" in wit_out


# --------------------------------------------------------------------------
# distilp_tpu/control/ (the closed-loop autoscaler) joins the repo-wide
# contracts: lazy-jax (DLP013), accounted excepts (DLP017), no blocking
# calls in async defs (DLP018), registered metric names (DLP019) and
# module-level ledger-registered jit (DLP020) — fixture-pinned so the
# prefix coverage cannot silently regress out from under the subsystem.


def test_control_module_joins_lazy_jax_contract():
    out = findings_for("DLP013", "distilp_tpu/control/controller.py", """\
        import jax

        def decide(signals):
            return jax.numpy.asarray(signals["queue_depth"])
        """)
    assert len(out) == 1 and "lazy" in out[0].message
    out = findings_for("DLP013", "distilp_tpu/control/controller.py", """\
        from distilp_tpu.ops.ipm import TRACE_COLS
        """)
    assert len(out) == 1


def test_control_module_joins_silent_except_contract():
    out = findings_for("DLP017", "distilp_tpu/control/controller.py", """\
        def actuate(self, gw, action):
            try:
                gw.spawn_worker()
            except RuntimeError:
                return None
        """)
    assert len(out) == 1 and "metrics sink" in out[0].message


def test_control_module_joins_async_blocking_contract():
    out = findings_for("DLP018", "distilp_tpu/control/exporter.py", """\
        import time

        async def push(self):
            time.sleep(0.1)
        """)
    assert len(out) == 1


def test_control_module_joins_metric_registry_contract():
    out = findings_for("DLP019", "distilp_tpu/control/controller.py", """\
        def step(self, metrics):
            metrics.inc("control_totally_unregistered")
        """)
    assert len(out) == 1 and "METRIC_REGISTRY" in out[0].message
    # ...while the registered autoscaler counters pass.
    out = findings_for("DLP019", "distilp_tpu/control/controller.py", """\
        def step(self, metrics):
            metrics.inc("control_actions")
            metrics.inc("control_scale_out")
        """)
    assert out == []


def test_control_module_joins_jit_registry_contract():
    out = findings_for("DLP020", "distilp_tpu/control/predictor.py", """\
        import jax

        def forecast(self, xs):
            step = jax.jit(lambda x: x * 2)
            return step(xs)
        """)
    assert len(out) == 1


def test_control_real_modules_are_currently_clean():
    """The REAL control/ package passes its layer's contracts."""
    from pathlib import Path

    for mod in ("__init__", "policy", "controller"):
        rel = f"distilp_tpu/control/{mod}.py"
        src = Path(rel).read_text()
        for code in ("DLP013", "DLP017", "DLP018", "DLP019", "DLP020"):
            assert findings_for(code, rel, src) == [], (rel, code)


# --------------------------------------------------------------------------
# crash-tolerance tier (ISSUE 20): the recovery module (WAL + snapshot
# store + supervisor) and the process-worker chaos surface ride the
# gateway/ prefix of every service-layer contract. Pinned per rule so a
# rename out of the prefix set fails HERE — not by silently un-linting
# the exactly-once machinery.


def test_recovery_module_joins_silent_except_contract():
    out = findings_for("DLP017", "distilp_tpu/gateway/recovery.py", """\
        def replay_tail(self):
            try:
                self._apply_records()
            except OSError:
                pass
        """)
    assert len(out) == 1 and "metrics sink" in out[0].message
    # The justified-disable escape the WAL's torn-tail scan and the
    # best-effort directory fsync use — reason required on the line.
    out = findings_for("DLP017", "distilp_tpu/gateway/recovery.py", """\
        def replay_tail(self):
            try:
                self._apply_records()
            except OSError:  # dlint: disable=DLP017 a torn tail record IS the crash being recovered; replay stops at the last durable frame
                pass
        """)
    assert out == []


def test_recovery_module_joins_lazy_jax_contract():
    out = findings_for("DLP013", "distilp_tpu/gateway/recovery.py", """\
        import jax

        def restore(self):
            return jax
        """)
    assert len(out) == 1


def test_recovery_module_joins_async_blocking_contract():
    out = findings_for("DLP018", "distilp_tpu/gateway/recovery.py", """\
        import time

        async def flush(self):
            time.sleep(0.1)
        """)
    assert len(out) == 1


def test_recovery_module_joins_metric_registry_contract():
    out = findings_for("DLP019", "distilp_tpu/gateway/recovery.py", """\
        def append(self, metrics):
            metrics.inc("wal_appendz")
        """)
    assert len(out) == 1 and "METRIC_REGISTRY" in out[0].message
    # ...while the registered supervision counters pass.
    out = findings_for("DLP019", "distilp_tpu/gateway/recovery.py", """\
        def append(self, metrics):
            metrics.inc("wal_appends")
            metrics.inc("micro_snapshots")
            metrics.inc("worker_crashes")
            metrics.inc("child_respawns")
            metrics.inc("events_replayed")
            metrics.inc("workers_quarantined")
        """)
    assert out == []


def test_recovery_module_joins_jit_registry_contract():
    out = findings_for("DLP020", "distilp_tpu/gateway/recovery.py", """\
        import jax

        def warm_restore(self, xs):
            step = jax.jit(lambda x: x + 1)
            return step(xs)
        """)
    assert len(out) == 1


def test_recovery_real_modules_are_currently_clean():
    """The REAL crash-tolerance modules pass their layer's contracts."""
    from pathlib import Path

    for mod in ("recovery", "snapshot", "procworker"):
        rel = f"distilp_tpu/gateway/{mod}.py"
        src = Path(rel).read_text()
        for code in ("DLP013", "DLP017", "DLP018", "DLP019", "DLP020"):
            assert findings_for(code, rel, src) == [], (rel, code)

"""Analytic-walker coverage for every registered architecture family.

The registry advertises 12 families (profiler/hfconfig.py ARCHS); the
model-specific suites cover llama/mistral/qwen3/qwen3_moe/gpt_oss/
deepseek_v3 via the reference's golden values. This file closes the other
six — gemma2, phi3 (fused gate_up), glm4 (fused + configured head_dim),
olmo3, qwen2, qwen2_moe (implicit shared expert) — with self-golden pins
generated from published architecture configs and sanity-checked against
parameter-count arithmetic (bytes/layer x L ~ params x bytes/weight).
A regression that moves any per-layer byte or FLOP count fails exactly.
"""

from __future__ import annotations

import pytest

from distilp_tpu.profiler.api import profile_model

# (config, L, b[1] bytes, f_q[b_1] decode FLOPs, quant, routed experts)
FAMILY_GOLDEN = [
    ("gemma2_9b", 42, 385351680.0, 387186688.0, "BF16", 0),
    ("phi3_mini", 32, 226492416.0, 228065280.0, "BF16", 0),
    ("glm4_9b", 40, 207134720.0, 409993216.0, "Q8_0", 0),
    ("olmo3_7b", 32, 404750336.0, 406847488.0, "BF16", 0),
    ("qwen2_7b_8bit", 28, 236687360.0, 467927040.0, "Q8_0", 0),
    ("qwen15_moe_a27b", 24, 1140850688.0, 173260800.0, "BF16", 60),
]


@pytest.mark.parametrize("cfg,L,b1,fq1,quant,E", FAMILY_GOLDEN)
def test_family_profiles_pinned(cfg, L, b1, fq1, quant, E):
    split = profile_model(
        f"tests/configs/{cfg}.json", batch_sizes=[1], sequence_length=128
    )
    model = split.to_model_profile()
    assert model.L == L
    assert split.b[1] == b1
    assert model.f_q["b_1"] == fq1
    assert str(model.Q) == quant
    assert model.n_routed_experts == E
    assert len(split.b) == L + 1  # index 0 = embedding pseudo-layer (b=0)
    assert all(x > 0 for x in split.b[1:])
    # Both phases present with positive decode FLOPs on every layer.
    for phase in ("prefill", "decode"):
        assert all(x > 0 for x in split.f_q[phase]["b_1"][1:])


def test_qwen2_moe_shared_expert_modeled():
    """Qwen2-MoE's single structural shared expert (config publishes only
    shared_expert_intermediate_size, never a count) must be priced: 3 GLU
    projections x hidden x shared-intermediate at the weight dtype."""
    split = profile_model(
        "tests/configs/qwen15_moe_a27b.json", batch_sizes=[1],
        sequence_length=128,
    )
    m = split.to_model_profile()
    assert m.n_shared_experts == 1
    k0 = sorted(split.bytes_per_expert)[0]
    assert split.bytes_shared_experts[k0] == 3 * 2048 * 5632 * 2
    assert split.bytes_per_expert[k0] == 3 * 2048 * 1408 * 2
    assert m.experts_per_token == 4


@pytest.mark.parametrize("cfg", ["glm4_9b", "qwen15_moe_a27b"])
def test_family_solves_end_to_end(cfg):
    """The two structurally novel families (fused-projection dense; MoE with
    implicit shared expert) must flow through the full placement stack on
    both backends, not just the profiler."""
    from distilp_tpu.solver import halda_solve
    from distilp_tpu.utils import make_synthetic_fleet

    model = profile_model(
        f"tests/configs/{cfg}.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()
    devs = make_synthetic_fleet(4, seed=3, pool_bytes=int(48e9))
    gap = 1e-3
    ref = halda_solve(devs, model, kv_bits="8bit", mip_gap=gap, backend="cpu")
    got = halda_solve(devs, model, kv_bits="8bit", mip_gap=gap, backend="jax")
    assert got.certified
    assert abs(got.obj_value - ref.obj_value) <= 2 * gap * abs(ref.obj_value) + 1e-9
    assert sum(got.w) * got.k == model.L
    if model.n_routed_experts:
        assert sum(got.y) == model.n_routed_experts

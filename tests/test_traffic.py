"""Open-loop traffic engine + gateway admission control.

Three layers of coverage:

- pure generator/trace tests (no solver): arrival determinism, diurnal
  and burst shaping, byte-exact trace round trips, and the committed
  ``tests/traces/openloop_*.jsonl`` regeneration pins (the
  spec_burst/spec_flap pattern);
- scheduler-level admission hooks (solver-backed, small fleets like
  test_spec): coalesced seq accounting, quarantine-in-batch, and the
  pressure near-match serve (mode='spec_near');
- gateway-level admission (fake schedulers where solves would only slow
  the point down): deterministic shedding + record-by-record flight
  reconciliation, coalesce batching + structural barriers, HTTP 429 +
  Retry-After, the worker_queue_depth gauge, queue-wait span depth, the
  ShardFacade concurrent-ingest read fix, and the admission-off
  byte-identical pin.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from pathlib import Path
from types import SimpleNamespace

import pytest

from distilp_tpu.gateway import (
    Gateway,
    GatewayHTTPServer,
    QueueFull,
    ShardFacade,
)
from distilp_tpu.obs import FlightRecorder, Tracer
from distilp_tpu.sched import (
    ChaosReport,
    DeviceDegrade,
    DeviceJoin,
    LoadTick,
    Scheduler,
    SchedulerMetrics,
    registry_help,
)
from distilp_tpu.traffic import (
    ArrivalConfig,
    generate_openloop_schedule,
    read_openloop_trace,
    shed_violations,
    write_openloop_trace,
)
from distilp_tpu.traffic.arrivals import is_openloop_trace
from distilp_tpu.traffic.openloop import execute_openloop
from distilp_tpu.utils import make_synthetic_fleet

GAP = 1e-3
KS = [4, 8]


@pytest.fixture(scope="module")
def model():
    from distilp_tpu.profiler.api import profile_model

    return profile_model(
        "tests/configs/llama31_8b_4bit.json", batch_sizes=[1],
        sequence_length=128,
    ).to_model_profile()


@pytest.fixture()
def fleet():
    return make_synthetic_fleet(4, seed=11)


def make_scheduler(fleet, model, **kw):
    kw.setdefault("mip_gap", GAP)
    kw.setdefault("kv_bits", "4bit")
    kw.setdefault("backend", "jax")
    kw.setdefault("k_candidates", KS)
    return Scheduler(fleet, model, **kw)


def _dump_items(items):
    return [(a, f, e.model_dump()) for a, f, e in items]


# -- the arrival generator --------------------------------------------------


def test_arrival_schedule_deterministic():
    cfg = ArrivalConfig(
        seed=5, duration_s=30, base_rate=3.0, diurnal_amplitude=0.4,
        diurnal_period_s=30, n_regions=2, burst_rate_per_region=0.1,
        burst_factor=2.5, burst_duration_s=5.0, fleet_seed=3,
    )
    s1, i1 = generate_openloop_schedule(cfg, 5)
    s2, i2 = generate_openloop_schedule(cfg, 5)
    assert s1 == s2 and _dump_items(i1) == _dump_items(i2)
    _, i3 = generate_openloop_schedule(cfg.model_copy(update={"seed": 6}), 5)
    assert _dump_items(i1) != _dump_items(i3)
    # Timestamps are sorted and inside the horizon; every fleet declared.
    ts = [it.at_s for it in i1]
    assert ts == sorted(ts) and ts[-1] < cfg.duration_s
    assert {it.fleet_id for it in i1} <= set(s1)


def test_diurnal_modulation_shapes_the_rate():
    # One full sine period over the horizon: the first half (sin > 0)
    # must carry visibly more arrivals than the second. Seeded, so the
    # inequality is a deterministic fact of the committed draw.
    cfg = ArrivalConfig(
        seed=2, duration_s=80, base_rate=4.0, diurnal_amplitude=0.9,
        diurnal_period_s=80,
    )
    _, items = generate_openloop_schedule(cfg, 4)
    first = sum(1 for it in items if it.at_s < 40)
    second = len(items) - first
    assert first > 1.5 * second


def test_regional_bursts_cluster_arrivals():
    base = ArrivalConfig(seed=9, duration_s=60, base_rate=2.0)
    bursty = base.model_copy(
        update={
            "n_regions": 2,
            "burst_rate_per_region": 0.08,
            "burst_factor": 5.0,
            "burst_duration_s": 6.0,
        }
    )
    _, quiet_items = generate_openloop_schedule(base, 6)
    _, burst_items = generate_openloop_schedule(bursty, 6)

    def max_bin(items):
        bins = [0] * 60
        for it in items:
            bins[int(it.at_s)] += 1
        return max(bins)

    # A live burst multiplies the whole region's rate: the busiest second
    # of the bursty draw is far above anything the plain process shows.
    assert max_bin(burst_items) >= max_bin(quiet_items) + 4
    assert len(burst_items) > len(quiet_items)


def test_openloop_trace_roundtrip_byte_exact(tmp_path):
    cfg = ArrivalConfig(seed=4, duration_s=20, base_rate=3.0)
    specs, items = generate_openloop_schedule(cfg, 3)
    p1 = tmp_path / "a.jsonl"
    p2 = tmp_path / "b.jsonl"
    write_openloop_trace(p1, specs, items)
    specs2, items2 = read_openloop_trace(p1)
    assert specs2 == specs and _dump_items(items2) == _dump_items(items)
    write_openloop_trace(p2, specs2, items2)
    assert p1.read_bytes() == p2.read_bytes()


def test_bundled_openloop_traces_match_generator(tmp_path):
    # The committed captures are seeded draws; pin the recipe so a
    # regenerated file is byte-for-byte the committed one (the
    # spec_burst/spec_flap regeneration pattern).
    recipes = {
        "tests/traces/openloop_diurnal_burst.jsonl": (
            ArrivalConfig(
                seed=7, duration_s=60.0, base_rate=2.0,
                diurnal_amplitude=0.6, diurnal_period_s=40.0, n_regions=3,
                burst_rate_per_region=0.05, burst_factor=3.0,
                burst_duration_s=8.0, scenario="drift", fleet_size=3,
                fleet_seed=11,
            ),
            6,
        ),
        "tests/traces/openloop_poisson.jsonl": (
            ArrivalConfig(
                seed=13, duration_s=45.0, base_rate=1.5, scenario="drift",
                fleet_size=3, fleet_seed=11,
            ),
            4,
        ),
    }
    for path, (cfg, n_fleets) in recipes.items():
        specs, items = generate_openloop_schedule(cfg, n_fleets)
        fresh = tmp_path / Path(path).name
        write_openloop_trace(fresh, specs, items)
        assert fresh.read_bytes() == Path(path).read_bytes(), path


def test_openloop_trace_detection_and_gateway_compat():
    from distilp_tpu.gateway.traces import is_gateway_trace, read_gateway_trace

    ol = "tests/traces/openloop_diurnal_burst.jsonl"
    assert is_openloop_trace(ol) is True
    assert is_openloop_trace("tests/traces/gateway_smoke_10f.jsonl") is False
    # An open-loop capture is a valid gateway trace (at_s ignored): the
    # same committed file replays closed-loop through `serve`.
    assert is_gateway_trace(ol)
    specs, items = read_gateway_trace(ol)
    _, ol_items = read_openloop_trace(ol)
    assert len(items) == len(ol_items) and len(specs) == 6
    # And a closed-loop trace is rejected by the open-loop reader.
    with pytest.raises(ValueError, match="at_s"):
        read_openloop_trace("tests/traces/gateway_smoke_10f.jsonl")


# -- scheduler-level admission hooks ---------------------------------------


def test_handle_coalesced_seq_accounting(fleet, model):
    events = [
        LoadTick(t_comm_jitter={fleet[1].name: 1.01 + 0.01 * i})
        for i in range(4)
    ]
    # Deep-copy BEFORE any handling: the scheduler mutates profiles in
    # place, and both schedulers must start from the same coefficients.
    co_fleet = [d.model_copy(deep=True) for d in fleet]
    seq_sched = make_scheduler(fleet, model)
    for ev in events:
        seq_sched.handle(ev)
    co_sched = make_scheduler(co_fleet, model)
    view = co_sched.handle_coalesced(events)
    c = co_sched.metrics.counters
    # Per-shard seq accounting: every event applied, seq advanced per
    # event, but only ONE solve ran and 3 events folded into it.
    assert co_sched.fleet.seq == 4 == c["events_total"]
    assert view.seq == 4 and view.events_behind == 0
    assert c["events_coalesced"] == 3
    assert sum(c[f"tick_{m}"] for m in ("cold", "warm", "margin")) == 1
    assert view.result.certified
    # The coalesced fleet state equals the sequentially-applied one.
    for a, b in zip(co_sched.fleet.device_list(), seq_sched.fleet.device_list()):
        assert a.t_comm == pytest.approx(b.t_comm)


def test_handle_coalesced_quarantines_poison(fleet, model):
    sched = make_scheduler(fleet, model)
    sched.handle(LoadTick(t_comm_jitter={}))  # publish something first
    events = [
        LoadTick(t_comm_jitter={fleet[1].name: 1.02}),
        DeviceDegrade(name=fleet[2].name, t_comm_scale=float("nan")),
        LoadTick(t_comm_jitter={fleet[1].name: 1.03}),
    ]
    view = sched.handle_coalesced(events)
    c = sched.metrics.counters
    assert c["events_quarantined"] == 1
    assert sched.fleet.seq == 3  # init tick + 2 applied; poison never lands
    assert view.events_behind == 0
    assert c["events_coalesced"] == 1  # one applied event folded


def test_spec_near_probe_serves_under_pressure(fleet, model):
    sched = make_scheduler(fleet, model, speculative=True)
    sched.handle(LoadTick(t_comm_jitter={}))  # solved + banked (certified)
    assert len(sched.spec_bank) >= 1
    # 12% drift: outside the 5% digest bucket (honest exact miss) but
    # within the default near radius (~22%).
    ev = LoadTick(t_comm_jitter={fleet[1].name: 1.12})
    view = sched.handle(ev, pressure=True)
    c = sched.metrics.counters
    assert view.mode == "spec_near"
    assert c["spec_near_hit"] == 1 and c["spec_miss"] >= 1
    assert view.events_behind == 0 and view.result.certified
    assert c.get("drift_tick_spec_near", 0) == 1


def test_spec_near_radius_bounds_the_match(fleet, model):
    sched = make_scheduler(fleet, model, speculative=True)
    sched.handle(LoadTick(t_comm_jitter={}))
    # 3x drift: ~22 tolerance buckets away — no near-match; the pressure
    # tick falls through to a real solve.
    view = sched.handle(
        LoadTick(t_comm_jitter={fleet[1].name: 3.0}), pressure=True
    )
    c = sched.metrics.counters
    assert view.mode in ("warm", "cold", "margin")
    assert c["spec_near_miss"] == 1 and c.get("spec_near_hit", 0) == 0


def test_pressure_off_never_near_serves(fleet, model):
    sched = make_scheduler(fleet, model, speculative=True)
    sched.handle(LoadTick(t_comm_jitter={}))
    view = sched.handle(LoadTick(t_comm_jitter={fleet[1].name: 1.12}))
    c = sched.metrics.counters
    assert view.mode != "spec_near"
    assert "spec_near_hit" not in c and "spec_near_miss" not in c


# -- gateway admission (fake schedulers: no solves needed) ------------------


class FakeScheduler:
    """Scheduler-shaped stub: instant (optionally gated) ticks, real
    metrics sink, coalesce-hook support, enough view surface for the
    executor's validity checks."""

    def __init__(self, gate: threading.Event | None = None):
        self.gate = gate
        self.metrics = SchedulerMetrics()
        self.health = "healthy"
        self.seq = 0
        self.batches: list = []

    def _view(self):
        # Full PlacementView surface: view_to_dict (the HTTP tier) reads
        # every field.
        return SimpleNamespace(
            result=SimpleNamespace(
                k=2, w=[1, 1], n=[4, 4], y=None, obj_value=1.0,
                certified=True, gap=0.0,
            ),
            seq=self.seq,
            fleet_seq=self.seq,
            events_behind=0,
            age_s=0.0,
            mode="warm",
            twin_p95_s=None,
            risk_selected=False,
        )

    def handle(self, event, pressure: bool = False):
        if self.gate is not None:
            self.gate.wait(timeout=30)
        self.seq += 1
        self.batches.append([event])
        self.metrics.inc("events_total")
        return self._view()

    def handle_coalesced(self, events, pressure: bool = False):
        if self.gate is not None:
            self.gate.wait(timeout=30)
        self.seq += len(events)
        self.batches.append(list(events))
        self.metrics.inc("events_total", len(events))
        return self._view()

    def health_snapshot(self):
        return {"state": "healthy"}

    def close(self):
        pass


def _fake_gateway(gate=None, **kw):
    devs = make_synthetic_fleet(2, seed=0)
    model = SimpleNamespace(L=8)
    gw = Gateway(
        n_workers=1,
        scheduler_factory=lambda d, m: FakeScheduler(gate),
        **kw,
    )
    gw.register_fleet("f0", devs, model)
    return gw


def _drift(i: int = 0):
    return LoadTick(t_comm_jitter={"x": 1.0 + 0.001 * i})


def test_gateway_sheds_when_queue_full_and_reconciles():
    gate = threading.Event()
    flight = FlightRecorder(capacity=64)
    gw = _fake_gateway(gate, max_queue_depth=2, flight=flight)
    try:
        results: list = []

        def _send(i):
            try:
                results.append(("ok", gw.handle_event("f0", _drift(i))))
            except QueueFull as e:
                results.append(("shed", e))

        threads = [
            threading.Thread(target=_send, args=(i,)) for i in range(6)
        ]
        # First event occupies the worker (gated); start senders one at a
        # time so queue depth grows deterministically: 1 running + 2
        # queued, the remaining 3 must shed.
        for t in threads:
            t.start()
            time.sleep(0.05)
        gate.set()
        for t in threads:
            t.join(timeout=30)
        sheds = [r for r in results if r[0] == "shed"]
        served = [r for r in results if r[0] == "ok"]
        assert len(sheds) == 3 and len(served) == 3
        for _, e in sheds:
            assert e.retry_after_s > 0 and e.depth >= 2
        snap = gw.metrics_snapshot()
        assert snap["counters"]["events_shed"] == 3
        assert gw.shed_counts() == {"f0": 3}
        # Record-by-record: 3 shed flight records, indices 1..3, each
        # with a positive Retry-After; the contract checker agrees.
        recs = [r for r in flight.snapshot("f0") if r.get("shed")]
        assert [r["shed_index"] for r in recs] == [1, 2, 3]
        assert all(r["retry_after_s"] > 0 for r in recs)
        assert shed_violations(gw, flight) == []
        # Tamper: an unexplained counter bump must be caught.
        gw.metrics.inc("events_shed")
        assert any(
            "shed accounting" in v for v in shed_violations(gw, flight)
        )
    finally:
        gate.set()
        gw.close()


def test_shed_reconciliation_tolerates_ring_overflow():
    # Shed records share the fleet ring with tick records; a long run of
    # served ticks after an early shed burst evicts the shed records.
    # That is an overflow artifact, not a contract break — but a ring
    # that NEVER filled with no shed records is a real violation.
    gate = threading.Event()
    flight = FlightRecorder(capacity=4)
    gw = _fake_gateway(gate, max_queue_depth=1, flight=flight)
    try:
        threads = []
        for i in range(4):  # 1 running + 1 queued + 2 shed
            t = threading.Thread(
                target=lambda i=i: _send_quietly(gw, i)
            )
            t.start()
            threads.append(t)
            time.sleep(0.05)
        gate.set()
        for t in threads:
            t.join(timeout=30)
        assert gw.shed_counts() == {"f0": 2}
        assert shed_violations(gw, flight) == []
        # Push the shed records out with newer tick records (capacity 4).
        for _ in range(5):
            flight.record("f0", {"seq": 0, "kind": "load", "mode": "warm"})
        assert not any(
            r.get("shed") for r in flight.snapshot("f0")
        )
        # Overflow explains the absence: still clean.
        assert shed_violations(gw, flight) == []
        # But a never-overflowed ring with a counted shed is a violation.
        fresh = FlightRecorder(capacity=64)
        fresh.record("f0", {"seq": 0, "kind": "load", "mode": "warm"})
        assert any(
            "never overflowed" in v for v in shed_violations(gw, fresh)
        )
    finally:
        gate.set()
        gw.close()


def _send_quietly(gw, i):
    try:
        gw.handle_event("f0", _drift(i))
    except QueueFull:
        pass


def test_gateway_coalesces_queued_drift_with_structural_barrier():
    gate = threading.Event()
    gw = _fake_gateway(gate, max_queue_depth=64, coalesce=True)
    try:
        boxes = []
        # d0 occupies the worker; d1..d3 join one pending batch; the
        # structural join is a barrier; d4/d5 open a fresh batch behind it.
        join_dev = make_synthetic_fleet(1, seed=99)[0]
        join_dev.name = "late-joiner"
        join_dev.is_head = False
        events = [
            _drift(0), _drift(1), _drift(2), _drift(3),
            DeviceJoin(device=join_dev), _drift(4), _drift(5),
        ]
        for ev in events:
            key, worker = gw._lookup("f0")
            boxes.append(
                gw._submit_tick("f0", key, worker, ev, None, None)
            )
            time.sleep(0.05)
        gate.set()
        for box, done in boxes:
            assert done.wait(timeout=30)
            assert "exc" not in box
        sched = gw.scheduler("f0")
        shapes = [
            [getattr(e, "kind", "?") for e in b] for b in sched.batches
        ]
        assert shapes == [
            ["load"], ["load", "load", "load"], ["join"], ["load", "load"],
        ]
        # Every waiter of the coalesced batch got the SAME view object.
        batch_views = [boxes[i][0]["result"] for i in (1, 2, 3)]
        assert batch_views[0] is batch_views[1] is batch_views[2]
        # The resume cursor advanced by every event, batched or not.
        assert gw.events_handled("f0") == len(events)
    finally:
        gate.set()
        gw.close()


def test_sequential_admission_is_inert():
    # Driven strictly sequentially (each event completes before the next
    # is submitted), admission can neither shed nor coalesce: counters
    # stay byte-identical to an admission-off gateway.
    plain = _fake_gateway()
    admitted = _fake_gateway(
        max_queue_depth=4, coalesce=True, degrade_depth=2
    )
    try:
        for i in range(8):
            plain.handle_event("f0", _drift(i))
            admitted.handle_event("f0", _drift(i))
        cp = plain.metrics_snapshot()["counters"]
        ca = admitted.metrics_snapshot()["counters"]
        assert cp == ca
        assert "events_shed" not in ca and "events_coalesced" not in ca
        assert all(len(b) == 1 for b in admitted.scheduler("f0").batches)
    finally:
        plain.close()
        admitted.close()


def test_http_429_carries_parseable_retry_after():
    import urllib.error
    import urllib.request

    gate = threading.Event()
    flight = FlightRecorder(capacity=16)
    gw = _fake_gateway(gate, max_queue_depth=1, flight=flight)

    def post(port):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/events",
            data=json.dumps(
                {"fleet": "f0", "event": {"kind": "load"}}
            ).encode(),
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, dict(r.headers), json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), json.loads(e.read())

    async def main():
        srv = GatewayHTTPServer(gw)
        await srv.start()
        loop = asyncio.get_running_loop()
        # Occupy the worker, then fill the 1-deep queue, then overflow.
        t1 = loop.run_in_executor(None, post, srv.port)
        await asyncio.sleep(0.2)
        t2 = loop.run_in_executor(None, post, srv.port)
        await asyncio.sleep(0.2)
        st3, headers3, body3 = await loop.run_in_executor(
            None, post, srv.port
        )
        gate.set()
        r1, r2 = await t1, await t2
        await srv.close()
        return r1, r2, (st3, headers3, body3)

    try:
        r1, r2, (st, headers, body) = asyncio.run(main())
        assert r1[0] == 200 and r2[0] == 200
        assert st == 429
        retry_after = headers.get("Retry-After")
        assert retry_after is not None and int(retry_after) >= 1
        assert body["retry_after_s"] > 0 and body["fleet"] == "f0"
        assert gw.metrics_snapshot()["counters"][
            "http_too_many_requests"
        ] == 1
        assert shed_violations(gw, flight) == []
    finally:
        gate.set()
        gw.close()


def test_worker_queue_depth_gauge_in_prometheus():
    from distilp_tpu.obs.export import parse_prometheus_text

    assert registry_help("worker_queue_depth") is not None
    gw = _fake_gateway()
    try:
        gw.handle_event("f0", _drift())
        text = gw.prometheus_text()
        assert 'distilp_worker_queue_depth{worker="0"} 0' in text
        parsed = parse_prometheus_text(text)
        names = {s[0] for s in parsed["samples"]}
        assert "distilp_worker_queue_depth" in names
        assert parsed["type"]["distilp_worker_queue_depth"] == "gauge"
    finally:
        gw.close()


def test_queue_wait_span_carries_depth():
    tracer = Tracer(capacity=256)
    gate = threading.Event()
    devs = make_synthetic_fleet(2, seed=0)
    gw = Gateway(
        n_workers=1,
        scheduler_factory=lambda d, m: FakeScheduler(gate),
        tracer=tracer,
    )
    try:
        gw.register_fleet("f0", devs, SimpleNamespace(L=8))
        gate.set()
        gw.handle_event("f0", _drift())
        waits = [
            s for s in tracer.spans() if s["name"] == "gateway.queue_wait"
        ]
        assert waits and all("depth" in s["attrs"] for s in waits)
        assert all(s["attrs"]["depth"] >= 0 for s in waits)
    finally:
        gw.close()


def test_chaos_report_flags_stray_admission_counters():
    def report(counters):
        return ChaosReport(
            records=[], views=[], injected={}, ticks_to_healthy=0,
            final_health="healthy", metrics={"counters": counters},
        )

    bad = report({"events_shed": 2}).violations()
    assert any("admission accounting" in v for v in bad)
    bad = report({"events_coalesced": 1}).violations()
    assert any("admission accounting" in v for v in bad)
    assert report({"events_total": 5}).violations() == []


def test_openloop_executor_fires_late_never_throttles():
    # Every event scheduled at t<=0.02s against a slow (50 ms) shard:
    # open-loop means all 6 are DISPATCHED essentially immediately and
    # lateness shows up in the measured latency, which must grow with
    # queue position rather than gate the generator.
    class SlowSched(FakeScheduler):
        def handle(self, event, pressure: bool = False):
            time.sleep(0.05)
            return super().handle(event, pressure)

    devs = make_synthetic_fleet(2, seed=0)
    gw = Gateway(
        n_workers=1, scheduler_factory=lambda d, m: SlowSched(None)
    )
    try:
        gw.register_fleet("f0", devs, SimpleNamespace(L=8))
        from distilp_tpu.traffic.arrivals import ScheduledEvent

        items = [
            ScheduledEvent(0.02 * i / 6, "f0", _drift(i)) for i in range(6)
        ]
        rep = asyncio.run(execute_openloop(gw, items))
        assert rep["offered"] == 6 and rep["served"] == 6
        assert rep["shed"] == 0 and rep["failed"] == 0
        # Six 50 ms ticks serialized behind a ~20 ms schedule: the worst
        # event waited for ~all of them.
        assert rep["max_ms"] >= 250
        assert rep["p99_ms"] >= rep["p50_ms"]
    finally:
        gw.close()


def test_facade_reads_sound_under_live_ingest(fleet, model):
    """Satellite pin: ShardFacade reads route through the worker queue,
    so a read under LIVE async ingest observes the shard at a tick
    boundary — fleet seq and published seq from one instant agree on a
    clean drift trace (a caller-side read could see seq advanced with
    the publish still in flight)."""
    gw = Gateway(
        n_workers=1,
        scheduler_kwargs=dict(
            mip_gap=GAP, kv_bits="4bit", backend="jax", k_candidates=KS
        ),
    )
    try:
        gw.register_fleet("live", fleet, model)
        facade = ShardFacade(gw, "live")
        n_events = 10
        stop = threading.Event()
        seqs: list = []
        errors: list = []

        def reader():
            while not stop.is_set():
                try:
                    view = facade.fleet
                except Exception as e:  # noqa: BLE001 - the test asserts below
                    errors.append(e)
                    return
                assert view.seq == (
                    view.published_seq or 0
                ), "read observed a mid-tick state"
                seqs.append(view.seq)

        async def ingest():
            for i in range(n_events):
                await gw.handle_event_async(
                    "live",
                    LoadTick(
                        t_comm_jitter={fleet[1].name: 1.0 + 0.002 * i}
                    ),
                )

        t = threading.Thread(target=reader)
        t.start()
        asyncio.run(ingest())
        stop.set()
        t.join(timeout=30)
        assert not errors
        assert seqs == sorted(seqs), "facade reads went back in time"
        assert facade.fleet.seq == n_events
        assert facade.metrics.counters["events_total"] == n_events
    finally:
        gw.close()

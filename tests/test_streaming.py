"""Warm-started re-solve and the streaming re-placement loop.

The warm incumbent must be re-priced under the NEW coefficients (a stale
objective would poison the mip-gap certificate), so a warm solve and a cold
solve must certify to the same answer — warm only changes how fast.
"""

from __future__ import annotations

import copy
import json

import pytest

from distilp_tpu.common import load_from_profile_folder
from distilp_tpu.solver import StreamingReplanner, halda_solve
from distilp_tpu.utils import make_synthetic_fleet

GAP = 1e-3


@pytest.fixture(scope="module")
def fleet_and_model():
    _, model = load_from_profile_folder("tests/profiles/llama_3_70b/online")
    devs = make_synthetic_fleet(8, seed=11)
    return devs, model


def _close(a, b, gap=GAP):
    return abs(a - b) <= 2 * gap * abs(b) + 1e-9


def test_warm_matches_cold(fleet_and_model):
    devs, model = fleet_and_model
    cold = halda_solve(devs, model, kv_bits="4bit", mip_gap=GAP, backend="jax")
    warm = halda_solve(
        devs, model, kv_bits="4bit", mip_gap=GAP, backend="jax", warm=cold
    )
    assert _close(warm.obj_value, cold.obj_value)
    assert sum(warm.w) * warm.k == model.L


def test_warm_survives_profile_drift(fleet_and_model):
    devs, model = fleet_and_model
    prev = halda_solve(devs, model, kv_bits="4bit", mip_gap=GAP, backend="jax")

    drifted = [copy.deepcopy(d) for d in devs]
    for d in drifted:
        d.t_comm *= 1.5
    cold = halda_solve(drifted, model, kv_bits="4bit", mip_gap=GAP, backend="jax")
    warm = halda_solve(
        drifted, model, kv_bits="4bit", mip_gap=GAP, backend="jax", warm=prev
    )
    # The stale assignment must be re-priced, not trusted: warm == cold.
    assert _close(warm.obj_value, cold.obj_value)


def test_warm_with_garbage_is_ignored(fleet_and_model):
    """A warm hint that no longer fits (wrong M) must not corrupt the solve."""
    devs, model = fleet_and_model
    cold = halda_solve(devs, model, kv_bits="4bit", mip_gap=GAP, backend="jax")
    small = halda_solve(
        devs[:2], model, kv_bits="4bit", mip_gap=GAP, backend="jax"
    )
    warm = halda_solve(
        devs, model, kv_bits="4bit", mip_gap=GAP, backend="jax", warm=small
    )
    assert _close(warm.obj_value, cold.obj_value)


def test_streaming_replanner_loop(fleet_and_model):
    devs, model = fleet_and_model
    planner = StreamingReplanner(mip_gap=GAP, kv_bits="4bit", backend="jax")

    first = planner.step(devs, model)
    assert planner.last is first

    # Tick 2: drifted fleet, same shape -> warm path.
    drifted = [copy.deepcopy(d) for d in devs]
    for d in drifted:
        d.t_comm *= 2.0
    second = planner.step(drifted, model)
    cold = halda_solve(drifted, model, kv_bits="4bit", mip_gap=GAP, backend="jax")
    assert _close(second.obj_value, cold.obj_value)

    # Tick 3: fleet shrinks -> shape change forces a cold solve, still correct.
    third = planner.step(drifted[:4], model)
    assert len(third.w) == 4 and sum(third.w) * third.k == model.L


def test_streaming_replanner_moe():
    from distilp_tpu.profiler.api import profile_model

    model = profile_model(
        "tests/configs/mixtral_8x7b.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    planner = StreamingReplanner(mip_gap=GAP, kv_bits="8bit", backend="jax")
    first = planner.step(devs, model)
    assert first.y is not None and sum(first.y) == model.n_routed_experts
    second = planner.step(devs, model)
    assert second.y is not None and sum(second.y) == model.n_routed_experts
    assert _close(second.obj_value, first.obj_value)


def test_warm_moe_from_dense_hint_repairs_y():
    """A warm hint lacking y (e.g. from a dense solve) must be repaired to a
    feasible expert placement, never returned raw with sum(y) != E."""
    from distilp_tpu.profiler.api import profile_model

    model = profile_model(
        "tests/configs/mixtral_8x7b.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    cold = halda_solve(devs, model, kv_bits="8bit", mip_gap=GAP, backend="jax")
    hint = cold.model_copy(update={"y": None})
    warm = halda_solve(
        devs, model, kv_bits="8bit", mip_gap=GAP, backend="jax", warm=hint
    )
    assert warm.y is not None and sum(warm.y) == model.n_routed_experts
    assert _close(warm.obj_value, cold.obj_value)


def test_moe_warm_tick_uses_stored_duals_and_certifies():
    """The real-time MoE re-placement path (BASELINE.json config 5): a warm
    tick must (a) carry Lagrangian root multipliers on its result, (b)
    re-certify against the bound EVALUATED at the stored duals — zero ascent
    steps, the design that makes the tick real-time — and (c) stay certified
    under profile drift."""
    import numpy as np

    from distilp_tpu.profiler.api import profile_model
    from distilp_tpu.solver import backend_jax

    model = profile_model(
        "tests/configs/mixtral_8x7b.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    planner = StreamingReplanner(mip_gap=GAP, kv_bits="8bit", backend="jax")

    first = planner.step(devs, model)
    # A cold MoE solve persists its root multipliers for the next tick.
    assert first.duals is not None
    n_k = len(first.duals["lam"])
    assert len(first.duals["mu"]) == n_k
    assert len(first.duals["tau"]) == n_k and len(first.duals["tau"][0]) == len(devs)
    assert all(np.isfinite(first.duals["lam"]))

    # Warm ticks run ZERO ascent steps (evaluation at stored duals only).
    assert backend_jax.DECOMP_STEPS_WARM == 0

    rng = np.random.default_rng(3)
    prev = first
    for _ in range(3):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.9, 1.1)))
        tick = planner.step(devs, model)
        assert tick.certified and tick.gap is not None and tick.gap <= GAP
        assert tick.y is not None and sum(tick.y) == model.n_routed_experts
        assert tick.duals is not None  # keeps flowing tick to tick
        prev = tick

    # The warm tick must match a cold solve on the same drifted fleet.
    cold = halda_solve(devs, model, kv_bits="8bit", mip_gap=GAP, backend="jax")
    assert _close(prev.obj_value, cold.obj_value)


def test_moe_warm_tick_falls_back_to_cold_when_uncertified(monkeypatch):
    """If drift makes the stored duals stale enough that the zero-step bound
    misses the certificate, the replanner must re-solve cold instead of
    returning an uncertified placement."""
    import warnings

    from distilp_tpu.profiler.api import profile_model
    from distilp_tpu.solver import streaming as streaming_mod

    model = profile_model(
        "tests/configs/mixtral_8x7b.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    planner = StreamingReplanner(mip_gap=GAP, kv_bits="8bit", backend="jax")
    planner.step(devs, model)

    calls = []
    orig = streaming_mod.halda_solve

    def spy(*args, **kwargs):
        # Record (warm?, anchor-present?) at CALL time: the middle rung of
        # the ladder must run with the anchor dropped (a true full
        # evaluation), not a duplicate margin tick on the same bounds.
        calls.append(
            (kwargs.get("warm") is not None,
             "m_y" in planner._margin_state)
        )
        result = orig(*args, **kwargs)
        if kwargs.get("warm") is not None:
            # Force the warm result to look uncertified.
            result = result.model_copy(update={"certified": False})
        return result

    monkeypatch.setattr(streaming_mod, "halda_solve", spy)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        tick = planner.step(devs, model)
    # The escalation ladder: the margin warm attempt, then a full-eval
    # warm retry (anchor cleared), then the cold fallback; the returned
    # result is the certified cold one.
    assert calls == [(True, True), (True, False), (False, True)]
    assert tick.certified


def test_moe_duals_without_usable_warm_hint_still_certifies():
    """A warm result whose k falls OUTSIDE the new k-grid is rejected as an
    incumbent hint, but its duals still shape-match and ride along. The
    zero-step warm mode must NOT engage then (it skips the Lagrangian
    primal repair, so without a warm incumbent the solve would start
    incumbent-less and miss the certificate); the solver must fall back to
    the full ascent and certify."""
    from distilp_tpu.profiler.api import profile_model

    model = profile_model(
        "tests/configs/mixtral_8x7b.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    prev = halda_solve(
        devs, model, kv_bits="8bit", mip_gap=GAP, backend="jax",
        k_candidates=[1, 2],
    )
    assert prev.duals is not None and prev.k in (1, 2)
    # New grid excludes prev.k, so the hint is unusable — but both grids
    # have n_k=2 feasible k's (W = 32/k >= M=4), so the stored duals still
    # pass the shape check and ride into the solve.
    got = halda_solve(
        devs, model, kv_bits="8bit", mip_gap=GAP, backend="jax",
        k_candidates=[4, 8], warm=prev,
    )
    assert got.certified and got.k in (4, 8)
    assert got.y is not None and sum(got.y) == model.n_routed_experts


def test_pipelined_ticks_match_sequential(fleet_and_model):
    """submit/collect with one tick in flight: every tick certified, warm
    hints one tick stale, final placement matching a cold solve."""
    devs, model = fleet_and_model
    devs = [copy.deepcopy(d) for d in devs]
    planner = StreamingReplanner(mip_gap=GAP, kv_bits="4bit", backend="jax")

    import numpy as np

    rng = np.random.default_rng(9)
    planner.submit(devs, model)  # tick 0 in flight
    results = []
    for _ in range(4):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.9, 1.1)))
        planner.submit(devs, model)  # tick t+1 dispatched...
        results.append(planner.collect())  # ...before tick t is redeemed
    results.append(planner.collect())
    assert all(r.certified for r in results)

    cold = halda_solve(devs, model, kv_bits="4bit", mip_gap=GAP, backend="jax")
    assert _close(results[-1].obj_value, cold.obj_value)


def test_pipeline_guards():
    planner = StreamingReplanner(backend="cpu")
    with pytest.raises(RuntimeError, match="jax"):
        planner.submit([], None)
    planner2 = StreamingReplanner(backend="jax")
    with pytest.raises(RuntimeError, match="in-flight"):
        planner2.collect()


def test_search_overrides_apply_to_every_tick(fleet_and_model, monkeypatch):
    import distilp_tpu.solver.streaming as streaming_mod

    devs, model = fleet_and_model
    captured = []
    real = streaming_mod.halda_solve

    def spy(*args, **kwargs):
        captured.append({k: kwargs.get(k) for k in ("beam", "ipm_iters")})
        return real(*args, **kwargs)

    monkeypatch.setattr(streaming_mod, "halda_solve", spy)
    # The dense problem-class defaults (beam 6 / 8 iters) passed explicitly:
    # the forwarding is observable without compiling a new device program.
    planner = StreamingReplanner(
        mip_gap=GAP, kv_bits="4bit", backend="jax",
        search={"beam": 6, "ipm_iters": 8},
    )
    planner.step(devs, model)
    planner.step(devs, model)  # warm tick forwards the same overrides
    assert len(captured) >= 2
    assert all(c == {"beam": 6, "ipm_iters": 8} for c in captured)
    with pytest.raises(ValueError, match="unknown search override"):
        StreamingReplanner(search={"beams": 8})


def test_submit_snapshot_is_shallow_but_freezes_scalars(fleet_and_model):
    """The pipelined snapshot (VERDICT r5 item 5): submit() must freeze the
    scalar state the streaming drift idiom mutates in place (t_comm *= ...)
    WITHOUT deep-copying the model's per-layer arrays and throughput tables
    every tick — the shallow model_copy() shares nested containers (drift
    REPLACES them, never mutates in place) while re-binding scalars."""
    devs, model = fleet_and_model
    devs = [copy.deepcopy(d) for d in devs]
    planner = StreamingReplanner(mip_gap=GAP, kv_bits="4bit", backend="jax")
    planner.submit(devs, model)
    (_, _, devs_snap, model_snap, *_rest) = planner._in_flight[0]

    # Scalars are frozen at submit time...
    t_before = devs_snap[0].t_comm
    devs[0].t_comm *= 7.0
    assert devs_snap[0].t_comm == t_before
    # ...while the heavy nested containers are shared, not duplicated.
    assert devs_snap[0].scpu is devs[0].scpu
    if model.f_q_layers is not None:
        assert model_snap.f_q_layers is model.f_q_layers
    assert model_snap.f_q is model.f_q
    # Replacing a container on the live profile does not leak into the
    # snapshot (the documented drift idiom for containers).
    old_loads = model.expert_loads
    model.expert_loads = [1.0]
    assert model_snap.expert_loads is old_loads
    model.expert_loads = old_loads

    result = planner.collect()  # drain the in-flight tick
    assert result.certified


# -- warm-state snapshot/restore (dump_warm_state / load_warm_state) -------
#
# The gateway's drain/restore cycle rides these: the round trip must be
# bit-exact, so a restored replanner's next tick — same drift applied —
# is IDENTICAL to the uninterrupted replanner's, on both LP engines.


@pytest.fixture(scope="module")
def small_fleet_and_model():
    """L=32 model + M=4 fleet: same shapes as tests/test_sched.py, so the
    jit programs are shared across modules within one pytest process."""
    from distilp_tpu.profiler.api import profile_model

    model = profile_model(
        "tests/configs/llama31_8b_4bit.json",
        batch_sizes=[1],
        sequence_length=128,
    ).to_model_profile()
    return make_synthetic_fleet(4, seed=11), model


@pytest.mark.parametrize("engine", ["ipm", "pdhg"])
def test_warm_blob_roundtrip_matches_uninterrupted(
    small_fleet_and_model, engine
):
    devs, model = small_fleet_and_model
    devs = [copy.deepcopy(d) for d in devs]
    ks = [4, 8]
    search = {"lp_backend": engine}
    if engine == "pdhg":
        search["pdhg_iters"] = 400  # tiny instance; full default is waste
    p = StreamingReplanner(
        mip_gap=GAP, kv_bits="4bit", backend="jax", search=search
    )
    p.step(devs, model, k_candidates=ks)
    for d in devs:
        d.t_comm *= 1.02
    p.step(devs, model, k_candidates=ks)

    # The blob is JSON all the way down (it rides GatewaySnapshot files).
    blob = json.loads(json.dumps(p.dump_warm_state()))
    q = StreamingReplanner(
        mip_gap=GAP, kv_bits="4bit", backend="jax", search=search
    )
    q.load_warm_state(blob)
    # Restored warm artifacts are bit-identical, not just close.
    assert q.last is not None and q.last.ipm_state is not None
    import numpy as np

    for key, arr in p.last.ipm_state.items():
        assert np.array_equal(np.asarray(arr), np.asarray(q.last.ipm_state[key]))
    assert q._last_shape == p._last_shape
    assert q.last.duals == p.last.duals

    for d in devs:
        d.t_comm *= 0.97
    r_uninterrupted = p.step(devs, model, k_candidates=ks)
    r_restored = q.step(devs, model, k_candidates=ks)
    assert q.last_tick_mode == "warm"  # the restore's whole point
    assert p.last_tick_mode == "warm"
    assert (
        r_restored.k,
        r_restored.w,
        r_restored.n,
        r_restored.obj_value,
    ) == (
        r_uninterrupted.k,
        r_uninterrupted.w,
        r_uninterrupted.n,
        r_uninterrupted.obj_value,
    )


def test_warm_blob_roundtrip_preserves_margin_anchor():
    """MoE: the margin fast path's anchor (rd exact-match fields + m_y
    profile + duals) must survive the round trip — the restored tick rides
    the MARGIN path, not merely warm."""
    from distilp_tpu.profiler.api import profile_model

    moe_model = profile_model(
        "tests/configs/mixtral_8x7b.json",
        batch_sizes=[1],
        sequence_length=128,
    ).to_model_profile()
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    p = StreamingReplanner(mip_gap=GAP, kv_bits="8bit", backend="jax")
    p.step(devs, moe_model)
    devs[1].t_comm *= 1.01
    p.step(devs, moe_model)

    blob = json.loads(json.dumps(p.dump_warm_state()))
    q = StreamingReplanner(mip_gap=GAP, kv_bits="8bit", backend="jax")
    q.load_warm_state(blob)

    devs[2].t_comm *= 1.02
    r_p = p.step(devs, moe_model)
    r_q = q.step(devs, moe_model)
    assert p.last_tick_mode == "margin"
    assert q.last_tick_mode == "margin"
    assert r_p.certified and r_q.certified
    assert (r_p.k, r_p.w, r_p.n, r_p.y, r_p.obj_value) == (
        r_q.k,
        r_q.w,
        r_q.n,
        r_q.y,
        r_q.obj_value,
    )


def test_warm_blob_refuses_in_flight_and_bad_version(small_fleet_and_model):
    devs, model = small_fleet_and_model
    planner = StreamingReplanner(mip_gap=GAP, kv_bits="4bit", backend="jax")
    planner.submit(devs, model, k_candidates=[4, 8])
    with pytest.raises(RuntimeError, match="in flight"):
        planner.dump_warm_state()
    planner.collect()
    blob = planner.dump_warm_state()
    blob["version"] = 99
    fresh = StreamingReplanner(mip_gap=GAP, kv_bits="4bit", backend="jax")
    with pytest.raises(ValueError, match="version"):
        fresh.load_warm_state(blob)

"""Process-backed shard workers (ISSUE 19 tentpole).

``ProcShardWorker`` hosts a worker's schedulers in a dedicated
subprocess behind a length-prefixed unix-socket RPC while presenting the
exact ``ShardWorker`` contract — so the Gateway's routing, coalescing,
snapshotting and migration machinery ride on top unchanged. These tests
pin the framing, the factory resolution, the backend gating, and the
end-to-end contract equivalence against thread workers (byte-identical
serving on the stub scheduler).

No jax in the child: the stub factory keeps every proc test in the
tier-1 wall-clock budget; the real-scheduler-in-child path is the bench
federation section's job.
"""

from __future__ import annotations

import socket
import threading

import pytest

from distilp_tpu.gateway import Gateway
from distilp_tpu.gateway.procworker import (
    ProcShardWorker,
    recv_frame,
    resolve_factory,
    send_frame,
)
from distilp_tpu.gateway.traces import make_fleet_from_spec

FACTORY = "tests.procstub:make_scheduler"


def _gateway(n_fleets: int, n_workers: int = 1, **kw) -> Gateway:
    gw = Gateway(
        n_workers=n_workers,
        scheduler_factory=FACTORY,
        worker_backend="process",
        **kw,
    )
    for i in range(n_fleets):
        fid = f"p{i:02d}"
        gw.register_fleet(
            fid, make_fleet_from_spec(fid, {"m": 3, "seed": 700 + i}), "stub"
        )
    return gw


# -- framing ---------------------------------------------------------------


def test_frame_roundtrip_over_socketpair():
    a, b = socket.socketpair()
    try:
        payloads = [
            {"op": "ping"},
            {"nested": {"list": [1, 2.5, "three"], "none": None}},
            {"big": "x" * 300_000},  # crosses many socket buffers
        ]
        got = []

        def reader():
            while True:
                obj = recv_frame(b)
                if obj is None:
                    return
                got.append(obj)

        t = threading.Thread(target=reader)
        t.start()
        for p in payloads:
            send_frame(a, p)
        a.close()  # clean EOF -> recv_frame returns None, reader exits
        t.join(timeout=10)
        assert got == payloads
    finally:
        b.close()


def test_recv_frame_none_on_immediate_eof():
    a, b = socket.socketpair()
    a.close()
    try:
        assert recv_frame(b) is None
    finally:
        b.close()


def test_recv_frame_raises_on_truncated_frame():
    a, b = socket.socketpair()
    try:
        # A length header promising bytes that never arrive is a torn
        # connection, not a clean shutdown — it must NOT read as EOF.
        a.sendall((1 << 20).to_bytes(8, "big") + b"short")
        a.close()
        with pytest.raises(EOFError):
            recv_frame(b)
    finally:
        b.close()


# -- factory resolution ----------------------------------------------------


def test_resolve_factory_roundtrip():
    from tests.procstub import make_scheduler

    assert resolve_factory(FACTORY) is make_scheduler


def test_resolve_factory_rejects_bad_specs():
    with pytest.raises(ValueError):
        resolve_factory("no_colon_here")
    with pytest.raises(ModuleNotFoundError):
        resolve_factory("definitely.not.a.module:fn")
    with pytest.raises(AttributeError):
        resolve_factory("tests.procstub:no_such_callable")


# -- backend gating --------------------------------------------------------


def test_process_backend_rejects_callable_factory():
    # A closure cannot cross a process boundary; only 'module:callable'
    # factory strings work on both backends.
    with pytest.raises(ValueError, match="factory"):
        Gateway(
            n_workers=1,
            scheduler_factory=lambda d, m: None,
            worker_backend="process",
        )


def test_process_backend_rejects_combine():
    with pytest.raises(ValueError, match="combine"):
        gw = Gateway(
            n_workers=1, scheduler_factory=FACTORY, worker_backend="process"
        )
        try:
            gw.configure_admission(combine=True)
        finally:
            gw.close()


def test_unknown_backend_rejected():
    with pytest.raises(ValueError, match="worker_backend"):
        Gateway(n_workers=1, worker_backend="fiber")


# -- end-to-end over the gateway -------------------------------------------


def test_proc_worker_serves_and_aggregates():
    gw = _gateway(n_fleets=3, n_workers=2)
    try:
        assert all(isinstance(w, ProcShardWorker) for w in gw.workers)
        for j in range(3):
            for fid in sorted(gw._fleet_key):
                view = gw.handle_event(fid, f"ev{j}")
                assert view["seq"] == j + 1
        # Reads cross the RPC: health, metrics, latest.
        health = gw.healthz()
        assert health["status"] == "healthy"
        totals = gw.metrics_snapshot()["shard_totals"]
        assert totals["events_total"] == 9
        assert gw.latest("p00")["seq"] == 3
    finally:
        gw.close()


def test_thread_and_process_backends_serve_identically():
    """Same trace, both backends: identical per-event payloads and
    identical aggregated shard counters — the contract seam is invisible
    to everything above the worker."""

    def run(backend: str):
        gw = Gateway(
            n_workers=2,
            scheduler_factory=FACTORY,
            worker_backend=backend,
        )
        try:
            for i in range(3):
                fid = f"t{i:02d}"
                gw.register_fleet(
                    fid,
                    make_fleet_from_spec(fid, {"m": 3, "seed": 800 + i}),
                    "stub",
                )
            views = [
                gw.handle_event(f"t{i:02d}", f"ev{j}")
                for j in range(4)
                for i in range(3)
            ]
            return views, gw.metrics_snapshot()["shard_totals"]
        finally:
            gw.close()

    views_t, totals_t = run("thread")
    views_p, totals_p = run("process")
    assert views_t == views_p
    assert totals_t == totals_p


def test_proc_spec_k_and_getattr_cross_the_wire():
    gw = _gateway(n_fleets=2)
    try:
        fid = sorted(gw._fleet_key)[0]
        gw.handle_event(fid, "e0")
        sched = gw.workers[0].shards[gw._fleet_key[fid]]
        assert sched.spec_k == 4  # stub default, read over RPC
        gw.set_spec_k(1)
        assert sched.spec_k == 1
        sched.spec_k = 6  # proxy setter
        assert sched.spec_k == 6
    finally:
        gw.close()


def test_proc_child_exception_reraises_in_parent():
    gw = _gateway(n_fleets=1)
    try:
        fid = sorted(gw._fleet_key)[0]
        key = gw._fleet_key[fid]
        gw.handle_event(fid, "before")
        sched = gw.workers[0].shards[key]
        with pytest.raises(KeyError):
            # load_state on the stub requires an 'events' key; the child's
            # KeyError must pickle back and re-raise here, not EOF.
            sched.load_state({"bogus": True})
        # The worker (and child) survive a failed call.
        assert gw.handle_event(fid, "after")["seq"] == 2
    finally:
        gw.close()


def test_proc_worker_stop_kills_child():
    gw = _gateway(n_fleets=1)
    worker = gw.workers[0]
    proc = worker._proc
    gw.close()
    assert proc.poll() is not None  # child exited
    # Idempotent: a second stop must not raise on the dead child.
    worker.stop()


def test_proc_dynamic_spawn_retire_migrates_live():
    """The autoscaler's actuation path on process workers: spawn moves
    shards to a fresh subprocess warm, retire moves them back, and the
    per-fleet seq chain never breaks."""
    gw = _gateway(n_fleets=4, dynamic=True)
    try:
        fleets = sorted(gw._fleet_key)
        for j in range(2):
            for fid in fleets:
                gw.handle_event(fid, f"ev{j}")
        _, moved = gw.spawn_worker()
        assert len(gw.live_worker_ids()) == 2
        for fid in fleets:
            assert gw.handle_event(fid, "mid")["seq"] == 3
        gw.retire_worker()
        assert gw.live_worker_ids() == [0]
        for fid in fleets:
            assert gw.handle_event(fid, "tail")["seq"] == 4
        counters = gw.metrics.snapshot()["counters"]
        assert counters.get("shards_migrated", 0) == 2 * len(moved)
        assert counters.get("migration_failed", 0) == 0
        # Warm hand-off reconciliation across the process boundary.
        totals = gw.metrics_snapshot()["shard_totals"]
        assert totals["warm_resumes"] == 2 * len(moved)
        assert totals["cold_resumes"] == 0
    finally:
        gw.close()


def test_proc_snapshot_roundtrips_to_thread_backend():
    """dump_state blobs are backend-neutral: a process-worker gateway's
    snapshot restores into a thread-worker gateway and resumes warm."""
    from distilp_tpu.gateway import GatewaySnapshot

    gw = _gateway(n_fleets=2)
    try:
        for fid in sorted(gw._fleet_key):
            gw.handle_event(fid, "e0")
        snap = gw.snapshot()
    finally:
        gw.close()
    assert isinstance(snap, GatewaySnapshot)
    gw2 = Gateway(n_workers=1, scheduler_factory=FACTORY)
    try:
        gw2.load_snapshot(snap)
        for fid in ("p00", "p01"):
            assert gw2.handle_event(fid, "e1")["seq"] == 2
        totals = gw2.metrics_snapshot()["shard_totals"]
        assert totals["warm_resumes"] == 2
    finally:
        gw2.close()


# -- crash taxonomy (ISSUE 20) ---------------------------------------------
#
# Three distinct deaths, three distinct surfaces: a torn RPC frame (the
# child refuses to parse a half-frame and exits nonzero), SIGKILL landing
# mid-solve (rc -9, the in-flight op named in the error), and a clean
# shutdown (exit 0, no crash counter — stop() is not a failure mode).


def test_torn_frame_mid_payload_is_worker_crashed_not_eof():
    from distilp_tpu.gateway.procworker import WorkerCrashed

    gw = _gateway(n_fleets=1)
    try:
        fid = sorted(gw._fleet_key)[0]
        gw.handle_event(fid, "ev0")
        gw.workers[0].inject_torn_frame()
        with pytest.raises(WorkerCrashed) as ei:
            gw.handle_event(fid, "ev1")
        err = ei.value
        # Typed for the HTTP ladder: NOT EOFError (client hangup, 400)
        # and NOT RuntimeError (conflict, 409).
        assert not isinstance(err, (EOFError, RuntimeError))
        assert err.worker_id == 0
        # A torn peer is a deliberate nonzero exit (the child's framing
        # layer refuses half a length header), NOT a SIGKILL.
        assert err.returncode is not None
        assert err.returncode != 0 and err.returncode != -9
    finally:
        gw.close()


def test_kill9_mid_solve_surfaces_sigkill_returncode():
    import time

    from distilp_tpu.gateway.procworker import WorkerCrashed

    gw = _gateway(n_fleets=1)
    try:
        fid = sorted(gw._fleet_key)[0]
        key = gw._fleet_key[fid]
        gw.handle_event(fid, "ev0")
        worker = gw.workers[0]
        worker.rpc(
            {
                "op": "setattr",
                "key": key,
                "name": "solve_sleep_s",
                "value": 1.0,
            }
        )
        crashed: list = []

        def tick():
            try:
                gw.handle_event(fid, "mid-solve")
            except BaseException as e:  # noqa: BLE001 - the assertion target
                crashed.append(e)

        t = threading.Thread(target=tick)
        t.start()
        time.sleep(0.3)  # let the RPC dispatch and the child enter the solve
        worker.kill_child()
        t.join(timeout=10.0)
        assert not t.is_alive()
        assert len(crashed) == 1 and isinstance(crashed[0], WorkerCrashed)
        assert crashed[0].returncode == -9  # SIGKILL, not a clean exit
        assert crashed[0].op is not None  # the in-flight op is named
    finally:
        gw.close()


def test_clean_shutdown_is_not_a_crash():
    gw = _gateway(n_fleets=1)
    fid = sorted(gw._fleet_key)[0]
    gw.handle_event(fid, "ev0")
    proc = gw.workers[0]._proc
    gw.close()
    assert proc.returncode == 0
    assert "worker_crashes" not in gw.metrics.snapshot()["counters"]

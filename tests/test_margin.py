"""Margin fast path: bound-reuse soundness and the streaming fallback chain.

A margin tick certifies against bounds REUSED from the last full
evaluation, corrected host-side for drift (backend_jax.
margin_bounds_from_state). The certificate is only as good as those
bounds, so this file pins the two things that matter:

1. SOUNDNESS — the reused bound never exceeds a fresh full evaluation at
   the same multipliers (fuzzed over drift classes); an overshoot would
   certify a placement the instance doesn't support.
2. ENGAGEMENT/GATING — the path engages on drift-class ticks (that's the
   latency win), refuses byte-class changes (pool sizes), and the
   replanner falls back full-eval-then-cold when the chain misses.
"""

from __future__ import annotations

import copy

import numpy as np
import pytest

from distilp_tpu.profiler.api import profile_model
from distilp_tpu.solver import StreamingReplanner, halda_solve
from distilp_tpu.solver import backend_jax as bj
from distilp_tpu.solver.api import _build_instance
from distilp_tpu.utils import make_synthetic_fleet

GAP = 1e-3


@pytest.fixture(scope="module")
def mixtral_model():
    return profile_model(
        "tests/configs/mixtral_8x7b.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()


def _standard_form(devs, model):
    Ks, _, coeffs, arrays = _build_instance(devs, model, None, "8bit", None, None)
    feasible = [(k, model.L // k) for k in Ks if model.L // k >= len(devs)]
    sf = bj.build_standard_form(arrays, coeffs, feasible)
    return sf, bj._rounding_arrays_np(coeffs, arrays.moe), arrays


def _fresh_bound(rd_np, sf, arrays, duals):
    import jax.numpy as jnp

    rd = bj.RoundingData(
        bprime=jnp.asarray(rd_np["bprime"], jnp.float64),
        E=jnp.asarray(rd_np["E"], jnp.float64),
        **{f: jnp.asarray(rd_np[f], jnp.float64) for f in bj._RD_VEC_FIELDS},
    )
    out = bj._decomp_bound_roots(
        rd,
        jnp.asarray(sf.ks, jnp.float64),
        jnp.asarray(sf.Ws, jnp.float64),
        max(sf.Ws),
        int(arrays.moe.E),
        steps=0,
        moe=True,
        init_params=tuple(jnp.asarray(p, jnp.float64) for p in duals),
    )
    return np.asarray(out[0])


def test_margin_bound_sound_vs_fresh_eval_fuzz(mixtral_model):
    """Across random t_comm AND expert-load drifts, the host-reused bound
    never exceeds the fresh on-device evaluation at the anchor duals (a
    hair of humility slack below it is expected and fine)."""
    model = mixtral_model
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    ms: dict = {}
    cold = halda_solve(
        devs, model, kv_bits="8bit", mip_gap=GAP, backend="jax",
        margin_state=ms,
    )
    assert "m_y" in ms and "rd" in ms, "full eval must store the anchor"
    duals = ms["duals"]

    rng = np.random.default_rng(5)
    checked = 0
    for trial in range(6):
        drifted = [copy.deepcopy(d) for d in devs]
        for d in drifted:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.8, 1.25)))
        lf = None
        if trial % 2:
            # Expert-load re-pricing drifts g_raw — the channel the exact
            # y-profile correction exists for.
            lf = [float(rng.uniform(0.5, 2.0)) for _ in drifted]
        Ks, _, coeffs, arrays = _build_instance(
            drifted, model, None, "8bit", None, lf
        )
        feasible = [
            (k, model.L // k) for k in Ks if model.L // k >= len(drifted)
        ]
        sf = bj.build_standard_form(arrays, coeffs, feasible)
        rd_np = bj._rounding_arrays_np(coeffs, arrays.moe)
        margin = bj.margin_bounds_from_state(ms, rd_np, sf, duals)
        assert margin is not None, "drift-class tick must be reusable"
        fresh = _fresh_bound(rd_np, sf, arrays, duals)
        for mb, fb in zip(margin, fresh):
            if np.isfinite(fb):
                assert mb <= fb + 1e-12, (mb, fb)
            checked += 1
        # Pure t_comm/load drift: the correction is exact up to the
        # humility slack, not just sound — the chain must not decay.
        if np.all(np.isfinite(fresh)):
            assert np.allclose(margin, fresh, rtol=1e-6, atol=1e-6)
    assert checked >= 6
    assert cold.certified


def test_margin_chain_does_not_decay_over_long_streams(mixtral_model):
    """50 drift ticks against ONE anchor: every tick margin-engaged and
    certified. The old subtract-a-slack design decayed each tick and died
    in a handful; the y-profile corrections are exact in the drift
    channels, so the chain survives indefinitely."""
    model = mixtral_model
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    planner = StreamingReplanner(mip_gap=GAP, kv_bits="8bit", backend="jax")
    planner.step(devs, model)
    anchor = planner._margin_state.get("m_y")
    rng = np.random.default_rng(11)
    for _ in range(50):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.97, 1.03)))
        tick = planner.step(devs, model)
        assert tick.certified
        # 'used' is consumed by the certification ladder each tick; the
        # replanner's mode attribute is the supported observable.
        assert planner.last_tick_mode == "margin"
    # The anchor was never refreshed: all 50 ticks reused one evaluation.
    assert planner._margin_state.get("m_y") is anchor


def test_margin_rides_pipelined_ticks(mixtral_model):
    """submit/collect MoE ticks engage the margin path too (the decision
    is taken at dispatch, the anchor refresh at collect), stay certified,
    and match a cold solve at the end of the stream."""
    model = mixtral_model
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    planner = StreamingReplanner(mip_gap=GAP, kv_bits="8bit", backend="jax")
    planner.step(devs, model)  # cold anchor
    rng = np.random.default_rng(13)
    planner.submit(devs, model)
    used = []
    results = []
    for _ in range(4):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
        planner.submit(devs, model)
        results.append(planner.collect())
        used.append(planner.last_tick_mode == "margin")
    results.append(planner.collect())
    assert all(r.certified for r in results)
    # A single miss-and-retry is LEGITIMATE (the retry resets "used" and
    # still certifies); what the contract promises is that the margin path
    # carries the stream, not that no tick ever escalates.
    assert sum(1 for u in used if u) >= len(used) - 1, (
        f"pipelined ticks did not ride the margin path: {used}"
    )
    cold = halda_solve(devs, model, kv_bits="8bit", mip_gap=GAP, backend="jax")
    assert (
        abs(results[-1].obj_value - cold.obj_value)
        <= 2 * GAP * abs(cold.obj_value) + 1e-9
    )


def test_margin_refuses_byte_class_changes(mixtral_model):
    """Pool-size (residency) changes reshape the feasibility staircases —
    the gate must refuse reuse and fall back to a full evaluation."""
    model = mixtral_model
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    ms: dict = {}
    halda_solve(
        devs, model, kv_bits="8bit", mip_gap=GAP, backend="jax",
        margin_state=ms,
    )
    duals = ms["duals"]
    grown = make_synthetic_fleet(4, seed=7, pool_bytes=int(96e9))
    Ks, _, coeffs, arrays = _build_instance(grown, model, None, "8bit", None, None)
    feasible = [(k, model.L // k) for k in Ks if model.L // k >= len(grown)]
    sf = bj.build_standard_form(arrays, coeffs, feasible)
    rd_np = bj._rounding_arrays_np(coeffs, arrays.moe)
    assert bj.margin_bounds_from_state(ms, rd_np, sf, duals) is None


def test_streaming_margin_ticks_engage_and_match_cold(mixtral_model):
    """The replanner's drift ticks ride the margin path (that's the
    latency claim) and still match a cold solve on the final fleet."""
    model = mixtral_model
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    planner = StreamingReplanner(mip_gap=GAP, kv_bits="8bit", backend="jax")
    planner.step(devs, model)
    rng = np.random.default_rng(3)
    used = []
    for _ in range(3):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.9, 1.1)))
        tick = planner.step(devs, model)
        used.append(planner.last_tick_mode == "margin")
        assert tick.certified
    assert all(used), f"margin path did not engage: {used}"
    cold = halda_solve(devs, model, kv_bits="8bit", mip_gap=GAP, backend="jax")
    assert abs(tick.obj_value - cold.obj_value) <= 2 * GAP * abs(cold.obj_value) + 1e-9
    # Fleet-shape change: margin must NOT leak across shapes (the gate
    # compares k-grids/rd shapes); the solve stays correct.
    small = planner.step(devs[:3], model)
    assert small.certified is not None and len(small.w) == 3

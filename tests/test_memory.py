"""Memory ledger (obs/memory.py) + analytic model (ops/memmodel.py).

Two tiers, the compile-ledger test economics:

- **Unit tier** (no solver): the /proc parsers (fixture texts including
  the missing-VmHWM kernel this repo's own CI runs on), the analytic
  model's parity with the formulas bench.py used inline before PR 15,
  graceful ``None`` when a backend reports no memory stats, sample
  throttling, the leak gate's structural re-pin, and the byte-stable
  JSONL round trip.
- **Solver tier**: real schedulers on the JAX CPU backend pin the tick
  attribution (span attrs + flight records + counters + mem.* timeline
  series), the additive-only contract (a live ledger changes no
  pre-existing counter), and THE invariant this module exists to guard:
  live-array bytes are FLAT across >= 100 steady-state warm ticks — on
  both LP engines.

Every test that enables a ledger disables it in a finally: the ledger
(and its dispatch hook) is process-global, exactly like the compile
ledger's.
"""

from __future__ import annotations

import pytest

from distilp_tpu.obs import memory
from distilp_tpu.obs import compile_ledger as cl
from distilp_tpu.obs.memory import (
    MemoryLedger,
    memory_from_jsonl,
    memory_to_jsonl,
    parse_proc_status,
    read_proc_status,
    render_report,
)
from distilp_tpu.ops import memmodel

GAP = 1e-3
KS = [4, 8]

# A real-shaped /proc/self/status excerpt (Linux) and the container
# kernel variant this repo's CI actually runs on: VmHWM absent entirely.
_STATUS_FULL = (
    "Name:\tpython\n"
    "VmPeak:\t  200000 kB\n"
    "VmSize:\t  150000 kB\n"
    "VmHWM:\t   99184 kB\n"
    "VmRSS:\t   98304 kB\n"
    "Threads:\t12\n"
)
_STATUS_NO_HWM = "Name:\tpython\nVmRSS:\t   6888 kB\nThreads:\t2\n"


# -- unit tier: /proc parsing -------------------------------------------------


def test_parse_proc_status_full():
    out = parse_proc_status(_STATUS_FULL)
    assert out == {
        "rss_bytes": 98304 * 1024,
        "hwm_bytes": 99184 * 1024,
    }


def test_parse_proc_status_missing_hwm_is_none_not_zero():
    out = parse_proc_status(_STATUS_NO_HWM)
    assert out["rss_bytes"] == 6888 * 1024
    assert out["hwm_bytes"] is None  # absent, never fabricated as 0


def test_parse_proc_status_garbage_lines_parse_to_none():
    out = parse_proc_status("VmRSS:\tnot-a-number kB\nVmHWM:\n")
    assert out == {"rss_bytes": None, "hwm_bytes": None}
    assert parse_proc_status("") == {"rss_bytes": None, "hwm_bytes": None}


def test_read_proc_status_missing_file_is_all_none():
    assert read_proc_status("/definitely/not/a/proc/path") == {
        "rss_bytes": None,
        "hwm_bytes": None,
    }


def test_read_meminfo_total(tmp_path):
    p = tmp_path / "meminfo"
    p.write_text("MemTotal:       139460608 kB\nMemFree: 1 kB\n")
    assert memory.read_meminfo_total(str(p)) == 139460608 * 1024
    assert memory.read_meminfo_total("/not/a/path") is None


# -- unit tier: the analytic model (ops/memmodel.py) --------------------------


@pytest.mark.parametrize("M", [16, 48, 512, 1024, 2048, 4096])
def test_memmodel_parity_with_the_old_inline_formulas(M):
    """PR 15 factored the fleet_scale proxies out of bench.py; the
    factored model must reproduce the inline formulas EXACTLY (these
    numbers decide which bench arms even run)."""
    beam = 6
    m_rows = 6 * M + 3
    assert memmodel.standard_form_dims(M) == (m_rows, 3 * M)
    assert memmodel.ipm_peak_bytes(M) == beam * m_rows * m_rows * 4
    assert memmodel.pdhg_peak_bytes(M) == m_rows * 3 * M * 4
    assert memmodel.peak_gb(M, "ipm") == pytest.approx(
        beam * m_rows * m_rows * 4 / 1e9
    )
    assert memmodel.peak_gb(M, "pdhg") == pytest.approx(
        m_rows * 3 * M * 4 / 1e9
    )


def test_memmodel_skip_decision_matches_the_old_bench_message():
    # The exact string fleet_scale rows carried before the factoring.
    reason = memmodel.ipm_memory_infeasible(4096, 8.0)
    gb = memmodel.peak_gb(4096, "ipm")
    assert reason == (
        f"memory-infeasible (~{gb:.1f} GB batched normal matrices "
        "> 8 GB cap)"
    )
    assert memmodel.ipm_memory_infeasible(512, 8.0) is None


def test_memmodel_rejects_bad_inputs():
    with pytest.raises(ValueError, match="fleet size"):
        memmodel.standard_form_dims(0)
    with pytest.raises(ValueError, match="unknown LP engine"):
        memmodel.peak_bytes(16, "simplex")


# -- unit tier: ledger mechanics ----------------------------------------------


@pytest.fixture()
def ledger():
    led = MemoryLedger(sample_min_interval_s=0.0)
    memory.enable(led)
    try:
        yield led
    finally:
        memory.disable()


class _FakeCompiled:
    def __init__(self, mem, cost):
        self._mem, self._cost = mem, cost

    def memory_analysis(self):
        if isinstance(self._mem, Exception):
            raise self._mem
        return self._mem

    def cost_analysis(self):
        return self._cost


class _FakeLowered:
    def __init__(self, compiled):
        self._compiled = compiled

    def compile(self):
        return self._compiled


class _FakeJit:
    """Stand-in for a jitted callable with an AOT surface."""

    def __init__(self, mem=None, cost=None):
        self.compiled = _FakeCompiled(mem, cost or [])

    def __call__(self, *a, **k):
        return a

    def lower(self, *a, **k):
        return _FakeLowered(self.compiled)


class _Stats:
    """memory_analysis()-shaped object (attribute access)."""

    temp_size_in_bytes = 1000
    argument_size_in_bytes = 200
    output_size_in_bytes = 30
    alias_size_in_bytes = 0
    generated_code_size_in_bytes = 4
    host_temp_size_in_bytes = 0


def test_analysis_records_memory_and_flops(ledger):
    fn = cl.instrument(
        "tests.mem.fake",
        _FakeJit(mem=_Stats(), cost=[{"flops": 7.0, "bytes accessed": 9.0}]),
    )
    fn(1, 2)
    rec = ledger.analyses["tests.mem.fake"]
    assert rec["memory"]["temp_bytes"] == 1000
    assert rec["memory"]["argument_bytes"] == 200
    assert rec["flops"] == 7.0 and rec["bytes_accessed"] == 9.0
    assert rec["error"] is None
    assert ledger.dispatches["tests.mem.fake"] == 1
    # Analyzed ONCE: a second dispatch only counts.
    fn(1, 2)
    assert ledger.dispatches["tests.mem.fake"] == 2
    assert ledger.analysis_errors == 0


def test_analysis_none_when_backend_reports_nothing(ledger):
    """The graceful-None contract: memory_analysis() returning None (a
    backend without buffer-assignment stats) records an entry with
    memory=None and NO error — absent, never zeroed, never fatal."""
    fn = cl.instrument("tests.mem.none", _FakeJit(mem=None, cost=[{"flops": 1.0}]))
    fn(1)
    rec = ledger.analyses["tests.mem.none"]
    assert rec["memory"] is None
    assert rec["error"] is None
    assert rec["flops"] == 1.0
    # And raising memory_analysis() is counted + surfaced, still not fatal.
    fn2 = cl.instrument(
        "tests.mem.raises", _FakeJit(mem=NotImplementedError("no stats"))
    )
    fn2(1)
    rec2 = ledger.analyses["tests.mem.raises"]
    assert rec2["memory"] is None
    assert rec2["error"] == "memory_analysis() unsupported"
    assert ledger.analysis_errors >= 1


def test_analysis_graceful_without_aot_lower(ledger):
    # Plain callables (the compile ledger's unit-tier stand-ins) have no
    # .lower: the entry records an explicit error, dispatch unaffected.
    fn = cl.instrument("tests.mem.plain", lambda x: x + 1)
    assert fn(41) == 42
    rec = ledger.analyses["tests.mem.plain"]
    assert rec["memory"] is None and "lower" in rec["error"]


def test_dispatch_hook_dormant_without_ledger():
    assert memory.current() is None
    fn = cl.instrument("tests.mem.dormant", _FakeJit(mem=_Stats()))
    fn(1)
    led = memory.enable(MemoryLedger())
    try:
        assert "tests.mem.dormant" not in led.analyses  # pre-enable call
        assert led.dispatches.get("tests.mem.dormant") is None
    finally:
        memory.disable()


def test_sample_throttle_returns_cached_between_intervals():
    led = MemoryLedger(sample_min_interval_s=3600.0)
    first = led.sample()
    assert first["fresh"] is True
    second = led.sample()
    assert second["fresh"] is False  # cached: inside the throttle window
    forced = led.sample(force=True)
    assert forced["fresh"] is True
    assert led.sample_count == 2  # the cached read counted no sample


def test_leak_gate_and_structural_repin():
    led = MemoryLedger(sample_min_interval_s=0.0)
    # Before mark_warm: no verdict, and note_structural is a no-op.
    assert led.leak_report() is None
    led.note_structural()
    assert led.leak_report() is None
    led.mark_warm()
    rep = led.leak_report()
    assert rep is not None and rep["flat"] and rep["growth_bytes"] == 0
    # Simulate growth: a fake newer sample with more live bytes.
    led._last = dict(led._last, live_bytes=led._last["live_bytes"] + 512)
    assert led.leak_report()["flat"] is False
    assert led.leak_report()["growth_bytes"] == 512
    assert led.leak_report(tolerance_bytes=512)["flat"] is True
    # A structural boundary re-pins: growth across it is provisioning.
    led.note_structural()
    assert led.leak_report()["flat"] is True


def test_headroom_budget_semantics():
    led = MemoryLedger(budget_bytes=None)
    assert led.headroom_bytes() is None  # no budget, no fabricated number
    led2 = MemoryLedger(budget_bytes=1 << 40)
    hr = led2.headroom_bytes()
    rss = read_proc_status()["rss_bytes"]
    if rss is None:
        assert hr is None
    else:
        assert hr is not None and 0 < hr < float(1 << 40)


def test_timeline_series_absent_not_zero():
    led = MemoryLedger(sample_min_interval_s=0.0, budget_bytes=1 << 40)
    series = led.timeline_series()
    status = read_proc_status()
    if status["rss_bytes"] is not None:
        assert series["mem.rss_bytes"] > 0
    if status["hwm_bytes"] is None:
        # This repo's CI kernel has no VmHWM: the series must be ABSENT,
        # not a zero-valued lie.
        assert "mem.hwm_bytes" not in series
    assert all(isinstance(v, float) for v in series.values())


def test_jsonl_round_trip_byte_stable_and_report_deterministic(ledger):
    fn = cl.instrument("tests.mem.dump", _FakeJit(mem=_Stats(), cost=[{"flops": 2.0}]))
    fn(1)
    ledger.sample(force=True)
    ledger.mark_warm()
    text = ledger.to_jsonl()
    dump = memory_from_jsonl(text)
    assert memory_to_jsonl(dump) == text
    r1 = render_report(dump)
    r2 = render_report(memory_from_jsonl(text))
    assert r1 == r2
    assert "tests.mem.dump" in r1 and "leak gate: FLAT" in r1


def test_from_jsonl_rejects_bad_dumps():
    with pytest.raises(ValueError, match="empty"):
        memory_from_jsonl("")
    with pytest.raises(ValueError, match="header"):
        memory_from_jsonl('{"not": "a header"}')
    with pytest.raises(ValueError, match="version"):
        memory_from_jsonl('{"memory_ledger": 99}')


def test_enable_resolves_budget_and_disable_detaches():
    led = memory.enable()
    try:
        assert memory.current() is led
        # Budget resolved from MemTotal where /proc exists.
        assert led.budget_bytes == memory.read_meminfo_total()
    finally:
        assert memory.disable() is led
        assert memory.current() is None


# -- solver tier: serving-path attribution ------------------------------------


@pytest.fixture(scope="module")
def model():
    from distilp_tpu.profiler.api import profile_model

    return profile_model(
        "tests/configs/llama31_8b_4bit.json", batch_sizes=[1],
        sequence_length=128,
    ).to_model_profile()


@pytest.fixture()
def fleet():
    from distilp_tpu.utils import make_synthetic_fleet

    return make_synthetic_fleet(4, seed=11)


def make_scheduler(fleet, model, **kw):
    from distilp_tpu.sched import Scheduler

    kw.setdefault("mip_gap", GAP)
    kw.setdefault("kv_bits", "4bit")
    kw.setdefault("backend", "jax")
    kw.setdefault("k_candidates", KS)
    return Scheduler(fleet, model, **kw)


def _drift_events(fleet, n, seed=5):
    from distilp_tpu.sched.sim import generate_trace

    return generate_trace("drift", n, seed=seed, base_fleet=fleet)


def test_ledger_off_path_is_byte_identical(fleet, model):
    """The additive-only pin: the same trace replayed with and without a
    live memory ledger produces IDENTICAL non-mem counters, and the mem
    counters/hists exist ONLY on the ledgered run."""
    events = _drift_events(fleet, 6)
    assert memory.current() is None
    sched_off = make_scheduler(fleet, model)
    for ev in events:
        sched_off.handle(ev)
    off = dict(sched_off.metrics.counters)
    sched_off.close()
    assert "mem_samples" not in off
    assert "mem_live_mb" not in sched_off.metrics.hists

    from distilp_tpu.utils import make_synthetic_fleet

    led = memory.enable(MemoryLedger(sample_min_interval_s=0.0))
    try:
        sched_on = make_scheduler(make_synthetic_fleet(4, seed=11), model)
        for ev in events:
            sched_on.handle(ev)
        on = dict(sched_on.metrics.counters)
        sched_on.close()
    finally:
        memory.disable()
    assert on.pop("mem_samples", 0) > 0
    assert off == on  # every pre-existing counter untouched


def test_tick_attribution_spans_flight_timeline(fleet, model):
    from distilp_tpu.obs.flight import FlightRecorder
    from distilp_tpu.obs.trace import Tracer

    led = memory.enable(MemoryLedger(sample_min_interval_s=0.0))
    try:
        tracer = Tracer(capacity=256)
        flight = FlightRecorder()
        sched = make_scheduler(fleet, model, tracer=tracer, flight=flight)
        for ev in _drift_events(fleet, 3):
            sched.handle(ev)
        c = sched.metrics.counters
        assert c["mem_samples"] > 0
        assert sched.metrics.hists["mem_live_mb"].count == c["mem_samples"]
        recs = [r for r in flight.snapshot("default") if "mem" in r]
        assert recs, "no flight record carries the mem watermark"
        assert all(r["mem"]["live_bytes"] > 0 for r in recs)
        # Solved ticks carry the watermark on the sched.solve span.
        solve_spans = [
            s for s in tracer.spans()
            if s["name"] == "sched.solve" and "mem_live_bytes" in s["attrs"]
        ]
        assert solve_spans
        # The per-entry static model rode the first Python-side dispatch.
        assert "solver._solve_packed" in led.analyses
        rec = led.analyses["solver._solve_packed"]
        assert rec["memory"] is not None
        assert rec["memory"]["temp_bytes"] > 0
        assert rec["flops"] and rec["flops"] > 0
        # Timeline series carry the watermark gauges while enabled.
        sample = sched.timeline_sample()
        assert sample["mem.live_bytes"] > 0
        assert sample["mem.rss_bytes"] > 0
        sched.close()
    finally:
        memory.disable()


def test_structural_tick_repins_leak_baseline(fleet, model):
    from distilp_tpu.sched.events import DeviceLeave

    led = memory.enable(MemoryLedger(sample_min_interval_s=0.0))
    try:
        sched = make_scheduler(fleet, model)
        for ev in _drift_events(fleet, 3):
            sched.handle(ev)
        led.mark_warm()
        # A structural event (identity change) legitimately re-allocates;
        # the scheduler re-pins the baseline so it reads as provisioning.
        sched.handle(DeviceLeave(name=fleet[3].name))
        led.sample(force=True)
        rep = led.leak_report()
        assert rep is not None and rep["flat"], rep
        sched.close()
    finally:
        memory.disable()


def test_gateway_mem_pressure_degrades_on_low_headroom():
    """The degrade-on-low-headroom admission hint: headroom below the
    floor marks ingest under pressure (counted as mem_pressure); no
    ledger, or headroom above the floor, never does — degrade on
    EVIDENCE, never on absence."""
    from distilp_tpu.gateway import Gateway

    gw = Gateway(
        n_workers=1,
        scheduler_factory=lambda d, m: None,
        mem_degrade_headroom_bytes=float(1 << 50),
    )
    try:
        assert gw._admission  # the knob alone engages the admission path
        assert gw._mem_pressure() is False  # no ledger: no verdict
        led = memory.enable(MemoryLedger(budget_bytes=1 << 40))
        try:
            if led.headroom_bytes() is None:
                pytest.skip("no readable RSS on this platform")
            # Floor of 1 PiB vs a 1 TiB budget: always under pressure.
            assert gw._mem_pressure() is True
            assert gw.metrics.counters["mem_pressure"] == 1
            # A generous floor clears it.
            gw.mem_degrade_headroom_bytes = 1.0
            assert gw._mem_pressure() is False
        finally:
            memory.disable()
        gw.mem_degrade_headroom_bytes = None
        assert gw._mem_pressure() is False
    finally:
        gw.close()


@pytest.mark.parametrize("lp_backend", ["ipm", "pdhg"])
def test_warm_serving_never_leaks_100_ticks(fleet, model, lp_backend):
    """THE zero-leak regression pin: across >= 100 steady-state warm
    drift ticks (speculation on — its bank donations and presolves
    included), live-array bytes show ZERO net growth, on both LP
    engines. This is the memory twin of the zero-recompile pin: a warm
    tick that pins arrays compounds into an OOM at fleet scale, and
    until now nothing would have caught it."""
    events = _drift_events(fleet, 105, seed=7)
    led = memory.enable(MemoryLedger())
    try:
        sched = make_scheduler(
            fleet, model, speculative=True, lp_backend=lp_backend
        )
        for ev in events[:5]:  # cold + warm layouts + scenario batch
            sched.handle(ev)
        led.mark_warm()
        for ev in events[5:]:
            sched.handle(ev)
        led.sample(force=True)
        rep = led.leak_report()
        assert rep is not None
        assert rep["growth_bytes"] <= 0, (
            f"warm serving grew live-array bytes under {lp_backend}: "
            f"{rep['baseline_bytes']} -> {rep['last_bytes']} "
            f"({rep['growth_bytes']:+d} B over {len(events) - 5} ticks)"
        )
        sched.close()
    finally:
        memory.disable()

"""Golden-objective tests for the CPU (scipy/HiGHS) backend.

The expected values were measured by running the reference solver on its own
fixtures (see BASELINE.md); matching them to 1e-6 proves the assembled MILP is
the same mathematical program.
"""

import pytest

from distilp_tpu.common import DeviceProfile, ModelProfile, load_from_profile_folder
from distilp_tpu.solver import halda_solve

GOLDEN = [
    # folder, k*, objective, w, n
    ("hermes_70b", 40, 29.643569, [2], [2]),
    ("llama_3_70b/4bit", 8, 12.834690, [10], [10]),
    ("llama_3_70b/online", 2, 1.934942, [13, 27], [13, 27]),
    ("qwen3_32b/bf16", 16, 12.072837, [4], [4]),
]


@pytest.mark.parametrize("folder,k_star,obj,w,n", GOLDEN)
def test_golden_objectives(profiles_dir, folder, k_star, obj, w, n):
    devs, model = load_from_profile_folder(profiles_dir / folder)
    result = halda_solve(devs, model, mip_gap=1e-4, kv_bits="4bit", backend="cpu")
    assert result.k == k_star
    assert result.obj_value == pytest.approx(obj, abs=1e-5)
    assert result.w == w
    assert result.n == n
    assert sum(result.w) * result.k == model.L


def test_k_candidates_honored(profiles_dir):
    devs, model = load_from_profile_folder(profiles_dir / "hermes_70b")
    result = halda_solve(
        devs, model, k_candidates=[8, 16], kv_bits="4bit", backend="cpu"
    )
    assert result.k in (8, 16)
    with pytest.raises(ValueError):
        halda_solve(devs, model, k_candidates=[3], kv_bits="4bit")  # 3 ∤ 80
    with pytest.raises(ValueError):
        halda_solve(devs, model, k_candidates=[80], kv_bits="4bit")  # k == L


def test_kv_bits_affects_objective(profiles_dir):
    devs, model = load_from_profile_folder(profiles_dir / "hermes_70b")
    r4 = halda_solve(devs, model, kv_bits="4bit", backend="cpu")
    r16 = halda_solve(devs, model, kv_bits="fp16", backend="cpu")
    # Heavier KV cache cannot make the plan cheaper.
    assert r16.obj_value >= r4.obj_value - 1e-9


def test_ram_overflow_spills_to_disk_slack():
    # One tiny device that cannot hold even one layer of a huge model.
    dev = DeviceProfile(
        name="tiny",
        os_type="linux",
        is_head=True,
        scpu={"F16": {"b_1": 1e9}},
        T_cpu=1e9,
        s_disk=1e6,
        d_avail_ram=1,  # 1 byte of RAM
        c_cpu=0,
    )
    model = ModelProfile(
        L=4,
        hk=8,
        ek=128,
        hv=8,
        ev=128,
        n_kv=1 << 20,
        e_embed=1024,
        V=1000,
        b_layer=1 << 40,  # 1 TiB per layer
        b_in=0,
        b_out=0,
        f_q={"b_1": 1.0},
        f_out={"b_1": 1.0},
        Q="F16",
    )
    # Slack variables make RAM overflow feasible (spill to disk) — the solver
    # should still return, charging the disk penalty.
    result = halda_solve([dev], model, kv_bits="8bit", backend="cpu")
    assert result.k >= 1


def test_infeasible_instance_raises():
    """More devices than layers per segment: sum w_i = W < M with w_i >= 1."""
    devs = [
        DeviceProfile(
            name=f"d{i}",
            os_type="linux",
            is_head=(i == 0),
            scpu={"F16": {"b_1": 1e9}},
            T_cpu=1e9,
            s_disk=1e6,
            d_avail_ram=1 << 30,
        )
        for i in range(4)
    ]
    model = ModelProfile(
        L=8, hk=1, ek=1, hv=1, ev=1, n_kv=1, e_embed=8, V=10,
        b_layer=1000, f_q={"b_1": 1.0}, f_out={"b_1": 1.0}, Q="F16",
    )
    # k=4 -> W=2 but M=4 devices each need w_i >= 1: infeasible for that k;
    # restricting candidates to k=4 must raise.
    with pytest.raises(RuntimeError, match="No feasible MILP"):
        halda_solve(devs, model, k_candidates=[4], kv_bits="8bit", backend="cpu")


def test_multi_device_sum_w(profiles_dir):
    devs, model = load_from_profile_folder(profiles_dir / "llama_3_70b" / "online")
    result = halda_solve(devs, model, kv_bits="4bit", backend="cpu")
    assert len(result.w) == 2
    assert sum(result.w) * result.k == model.L
    # n_i <= w_i everywhere
    for wi, ni in zip(result.w, result.n):
        assert 0 <= ni <= wi

"""Digital-twin evaluation tests (distilp_tpu.twin).

The twin's conformance contract: deterministically executing a placement
must reproduce the HALDA objective of that placement exactly (same
coefficient vocabulary, optimal stall/spill completion in closed form), so
twin latency and solver objective must RANK candidate placements
identically. Pinned here on all four golden fixtures plus the 16-device
north star, over solver-enumerated k-candidates.

The Monte-Carlo engine is pinned for: base-row agreement with the host
numpy oracle, determinism under a fixed PRNG seed, finite per-device
totals, feasibility-violation detection, sensitivity ranking, and the
risk-aware scheduler wiring (served placement changes on the bundled churn
trace).
"""

from __future__ import annotations

import numpy as np
import pytest

from distilp_tpu.common import (
    DeviceProfile,
    ModelProfile,
    load_from_profile_folder,
    load_model_profile,
)
from distilp_tpu.solver import HALDAResult, halda_solve
from distilp_tpu.solver.api import _build_instance
from distilp_tpu.solver.backend_cpu import Infeasible, solve_fixed_k_cpu
from distilp_tpu.twin import (
    build_twin_arrays,
    evaluate_placement,
    placement_applicable,
    rank_agreement,
    robustness_report,
    simulate_placement,
    twin_p95_score,
)
from distilp_tpu.utils import make_synthetic_fleet

GOLDEN_FOLDERS = [
    "hermes_70b",
    "llama_3_70b/4bit",
    "llama_3_70b/online",
    "qwen3_32b/bf16",
]


def _per_k_cpu(devs, model, kv_bits="4bit", k_candidates=None, moe=False):
    """Certified per-k optima via the HiGHS oracle (fast, exact)."""
    Ks, sets, _, arrays = _build_instance(
        devs, model, k_candidates, kv_bits, moe, None
    )
    out = []
    for k in Ks:
        try:
            res = solve_fixed_k_cpu(arrays, k, model.L // k, mip_gap=1e-6)
        except Infeasible:
            continue
        out.append(
            HALDAResult(
                w=res.w, n=res.n, k=res.k, y=res.y, obj_value=res.obj_value,
                sets={name: list(v) for name, v in sets.items()},
            )
        )
    return out


# --------------------------------------------------------------------------
# twin-vs-objective agreement (the satellite's golden contract)


@pytest.mark.parametrize("folder", GOLDEN_FOLDERS)
def test_twin_matches_objective_on_golden_fixtures(profiles_dir, folder):
    devs, model = load_from_profile_folder(profiles_dir / folder)
    result = halda_solve(devs, model, mip_gap=1e-4, kv_bits="4bit", backend="cpu")
    ev = evaluate_placement(devs, model, result, kv_bits="4bit")
    assert ev.rel_err is not None and ev.rel_err < 1e-9
    assert ev.feasible
    assert ev.k == result.k
    # Per-device totals must be finite and the breakdown must sum to the
    # busy time the cycle bound reads.
    for row in ev.devices:
        assert np.isfinite(row.busy_s)
        assert row.busy_s == pytest.approx(
            row.compute_s + row.disk_s + row.comm_s + row.offload_s
        )


@pytest.mark.parametrize("folder", GOLDEN_FOLDERS)
def test_twin_ranks_k_candidates_like_objective(profiles_dir, folder):
    devs, model = load_from_profile_folder(profiles_dir / folder)
    per_k = _per_k_cpu(devs, model)
    assert len(per_k) >= 2
    ra = rank_agreement(devs, model, per_k, kv_bits="4bit")
    assert ra["pairwise_inversions"] == 0
    assert ra["spearman"] == pytest.approx(1.0)
    assert all(np.isfinite(x) for x in ra["twin_latencies"])


def test_twin_matches_objective_and_ranks_moe():
    """The MoE branches (g_raw/k·y compute, expert-byte memory rows,
    s<=w / t<=n slack caps) carry the same conformance contract as the
    dense path: exact objective agreement and rank agreement over the
    per-k optima — pinned on the Mixtral-8x7B analytic profile via the
    HiGHS oracle (no jax MoE compile needed)."""
    from distilp_tpu.profiler.api import profile_model

    split = profile_model(
        "tests/configs/mixtral_8x7b.json", batch_sizes=[1], sequence_length=128
    )
    model = split.to_model_profile()
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    per_k = _per_k_cpu(
        devs, model, kv_bits="8bit", k_candidates=[2, 4, 8], moe=True
    )
    assert len(per_k) >= 2
    best = min(per_k, key=lambda r: r.obj_value)
    assert best.y is not None and sum(best.y) == model.n_routed_experts
    ev = evaluate_placement(devs, model, best, kv_bits="8bit", moe=True)
    assert ev.rel_err is not None and ev.rel_err < 1e-9
    ra = rank_agreement(devs, model, per_k, kv_bits="8bit", moe=True)
    assert ra["pairwise_inversions"] == 0
    assert ra["spearman"] == pytest.approx(1.0)
    # The MC engine prices the expert rows too: deterministic + finite.
    rep = robustness_report(
        devs, model, best, samples=32, seed=0, kv_bits="8bit", moe=True
    )
    assert rep.base_latency_s == pytest.approx(ev.latency_s, rel=1e-5)
    assert np.isfinite(rep.p95_s)


def test_twin_ranks_north_star_like_objective():
    model = load_model_profile(
        "tests/profiles/llama_3_70b/online/model_profile.json"
    )
    devs = make_synthetic_fleet(16, seed=123)
    per_k = _per_k_cpu(devs, model)
    assert len(per_k) >= 2  # W >= M leaves k in {1, 2, 4, 5}
    ra = rank_agreement(devs, model, per_k, kv_bits="4bit")
    assert ra["pairwise_inversions"] == 0
    assert ra["spearman"] == pytest.approx(1.0)


# --------------------------------------------------------------------------
# Monte-Carlo engine: oracle agreement, determinism, finiteness


@pytest.fixture(scope="module")
def online_solved():
    devs, model = load_from_profile_folder("tests/profiles/llama_3_70b/online")
    result = halda_solve(devs, model, mip_gap=1e-4, kv_bits="4bit", backend="cpu")
    return devs, model, result


def test_engine_base_row_matches_numpy_oracle(online_solved):
    devs, model, result = online_solved
    rep = robustness_report(devs, model, result, samples=64, seed=0, kv_bits="4bit")
    ev = evaluate_placement(devs, model, result, kv_bits="4bit")
    # f32 device math vs f64 host oracle: agreement to f32 resolution.
    assert rep.base_latency_s == pytest.approx(ev.latency_s, rel=1e-5)


def test_mc_report_deterministic_for_fixed_key(online_solved):
    devs, model, result = online_solved
    kw = dict(samples=128, kv_bits="4bit", dropout_p=0.05, sigma_mem=0.05)
    a = robustness_report(devs, model, result, seed=11, **kw)
    b = robustness_report(devs, model, result, seed=11, **kw)
    assert a.model_dump() == b.model_dump()
    c = robustness_report(devs, model, result, seed=12, **kw)
    assert c.p95_s != a.p95_s
    for rep in (a, c):
        for v in (rep.mean_s, rep.p50_s, rep.p95_s, rep.p99_s, rep.worst_s):
            assert np.isfinite(v)
        assert rep.p50_s <= rep.p95_s <= rep.p99_s <= rep.worst_s
        assert 0.0 <= rep.p_violation <= 1.0
        assert len(rep.sensitivity) == len(devs)


def test_sensitivity_ranks_bottleneck_first():
    # Device 0 is made the overwhelming bottleneck (a dominating link
    # cost): degrading it must cost more latency than degrading the other.
    devs = make_synthetic_fleet(2, seed=3)
    devs[0].t_comm = 0.5
    model = load_model_profile(
        "tests/profiles/llama_3_70b/online/model_profile.json"
    )
    result = halda_solve(devs, model, mip_gap=1e-3, kv_bits="4bit", backend="cpu")
    rep = robustness_report(devs, model, result, samples=32, seed=0, kv_bits="4bit")
    assert rep.sensitivity[0].name == devs[0].name
    assert rep.sensitivity[0].delta_s > rep.sensitivity[1].delta_s
    assert rep.sensitivity[0].share > 0.5


def _tiny_overflow_instance():
    dev = DeviceProfile(
        name="tiny",
        os_type="linux",
        is_head=True,
        scpu={"F16": {"b_1": 1e9}},
        T_cpu=1e9,
        s_disk=1e6,
        d_avail_ram=1,
        c_cpu=0,
    )
    model = ModelProfile(
        L=4, hk=8, ek=128, hv=8, ev=128, n_kv=1 << 20, e_embed=1024, V=1000,
        b_layer=1 << 30, b_in=0, b_out=0, f_q={"b_1": 1.0}, f_out={"b_1": 1.0},
        Q="F16",
    )
    return dev, model


def test_ram_overflow_spills_but_stays_feasible():
    # All layers overflow 1 byte of RAM; the slack capacity (W layers) can
    # absorb the spill, so the twin charges disk and stays feasible — same
    # semantics as the MILP's slack variables.
    dev, model = _tiny_overflow_instance()
    result = halda_solve([dev], model, kv_bits="8bit", backend="cpu")
    ev = evaluate_placement([dev], model, result, kv_bits="8bit")
    assert ev.feasible
    assert ev.devices[0].spill_layers == ev.devices[0].w
    assert ev.rel_err is not None and ev.rel_err < 1e-9


def test_infeasible_placement_flags_violation():
    # Hand the twin a placement whose expert bytes CANNOT fit: a MoE-free
    # trick is impossible (dense spill always fits W), so force it by
    # shrinking the slack cap: w=2 layers but spill needs 4 (k=2 -> W=2
    # per segment against 4 overflowing layers is fine; instead check the
    # violation channel through memory jitter collapsing capacity).
    dev, model = _tiny_overflow_instance()
    result = halda_solve([dev], model, kv_bits="8bit", backend="cpu")
    arrays = build_twin_arrays([dev], model, kv_bits="8bit")
    # Monkeyed cap: pretend the device may stream at most 0 layers. The
    # MILP bound (W) is placement-level; the twin must flag exceeding it.
    vec_ev = simulate_placement(arrays, result.w, result.n, k=result.k)
    assert vec_ev.feasible
    arrays.ram_rhs[:] = -1e18  # capacity collapses far beyond slack reach?
    # ram deficit grows, but spill cap W still absorbs ceil(deficit/bp)
    # only up to W layers; a deficit beyond W*bp means violation.
    ev2 = simulate_placement(arrays, result.w, result.n, k=result.k)
    assert not ev2.feasible
    rep = robustness_report(
        [dev], model, result, samples=16, seed=0, kv_bits="8bit", arrays=arrays
    )
    assert rep.p_violation == pytest.approx(1.0)


def test_placement_applicable_filters():
    devs, model = load_from_profile_folder("tests/profiles/llama_3_70b/online")
    arrays = build_twin_arrays(devs, model, kv_bits="4bit")
    assert placement_applicable(arrays, [13, 27], [13, 27], k=2)
    assert not placement_applicable(arrays, [13, 27, 1], [13, 27, 0], k=2)  # M
    assert not placement_applicable(arrays, [13, 27], [14, 27], k=2)  # n > w
    assert not placement_applicable(arrays, [13, 26], [13, 26], k=2)  # sum w
    assert not placement_applicable(arrays, [0, 40], [0, 40], k=2)  # w >= 1
    assert not placement_applicable(arrays, [13, 27], [13, 27], k=2, y=[1, 0])


def test_twin_p95_score_prefers_feasible(online_solved):
    devs, model, result = online_solved
    ok = twin_p95_score(devs, model, result, samples=32, seed=0, kv_bits="4bit")
    arrays = build_twin_arrays(devs, model, kv_bits="4bit")
    arrays.ram_rhs[:] = -1e18
    bad = twin_p95_score(
        devs, model, result, samples=32, seed=0, kv_bits="4bit", arrays=arrays
    )
    assert bad["p_violation"] == pytest.approx(1.0)
    assert bad["score"] > ok["score"] + 100.0  # violation penalty dominates
    # The penalty has a fixed step at p_violation > 0 (not just a graded
    # term): ANY violating candidate must lose to every violation-free one.
    from distilp_tpu.twin.api import VIOLATION_PENALTY_S

    assert bad["score"] >= bad["p95_s"] + VIOLATION_PENALTY_S


# --------------------------------------------------------------------------
# risk-aware scheduler: serving changes on the bundled churn trace


def test_risk_aware_changes_served_placement_on_bundled_trace():
    from distilp_tpu.sched import Scheduler, read_trace

    model = load_model_profile(
        "tests/profiles/llama_3_70b/online/model_profile.json"
    )
    # The first event of the bundled smoke trace is enough: the switch
    # happens on the very first tick (the objective prefers k=10 by a
    # hair; the twin's straggler channel prefers the shallower k=8). One
    # event also keeps tier-1 lean — only the M=4 fleet shape compiles.
    events = read_trace("tests/traces/scheduler_smoke_20.jsonl")[:1]
    served = {}
    metrics = {}
    for risk in (False, True):
        devs = make_synthetic_fleet(4, seed=11)
        sched = Scheduler(
            devs, model, mip_gap=1e-3, kv_bits="4bit", backend="jax",
            k_candidates=[8, 10], risk_aware=risk,
        )
        views = [sched.handle(ev) for ev in events]
        served[risk] = [(v.result.k, tuple(v.result.w)) for v in views]
        metrics[risk] = sched.metrics.counters
        if risk:
            assert all(v.twin_p95_s is not None for v in views)
            assert any(v.risk_selected for v in views)
    assert served[True] != served[False]
    assert metrics[True]["risk_eval"] == len(events)
    assert metrics[True]["risk_switch"] >= 1
    assert metrics[True]["risk_error"] == 0
    assert "risk_eval" not in metrics[False]


def test_risk_aware_deterministic_replay():
    from distilp_tpu.sched import Scheduler, read_trace

    model = load_model_profile(
        "tests/profiles/llama_3_70b/online/model_profile.json"
    )
    events = read_trace("tests/traces/scheduler_smoke_20.jsonl")[:1]

    def run():
        devs = make_synthetic_fleet(4, seed=11)
        sched = Scheduler(
            devs, model, mip_gap=1e-3, kv_bits="4bit", backend="jax",
            k_candidates=[8, 10], risk_aware=True,
        )
        return [
            (v.result.k, tuple(v.result.w), v.risk_selected, v.twin_p95_s)
            for v in (sched.handle(ev) for ev in events)
        ]

    assert run() == run()


def test_risk_mc_override_plumbs_through():
    from distilp_tpu.sched import Scheduler
    from distilp_tpu.sched.scheduler import DEFAULT_RISK_MC

    model = load_model_profile(
        "tests/profiles/llama_3_70b/online/model_profile.json"
    )
    devs = make_synthetic_fleet(2, seed=5)
    sched = Scheduler(
        devs, model, kv_bits="4bit", backend="cpu", risk_aware=True,
        risk_mc={"sigma_compute": 0.5, "dropout_p": 0.0},
    )
    assert sched.risk_mc == {"sigma_compute": 0.5, "dropout_p": 0.0}
    assert DEFAULT_RISK_MC["dropout_p"] > 0  # serving default keeps stragglers

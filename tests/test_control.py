"""Closed-loop autoscaler: policy bands, hysteresis, cooldown, replay.

The ``Controller`` contract pinned three ways:

- **decide** is a deterministic function of (signals, now, n_workers)
  with the two stabilizers — asymmetric hysteresis (scale-out on any
  one vote, scale-in only after EVERY calm condition holds for
  ``calm_hold_s``) and the scale cooldown — exercised on synthetic
  payloads, no gateway, no clock.
- **step** is decide + the accounting contract: every action counted
  (``control_actions`` + per-kind) and flight-recorded WITH the signals
  snapshot that justified it.
- **replay** is pure: the committed timeline + committed policy
  reproduce the committed action fixture byte-for-byte, twice.

The live actuation path (ControlLoop driving a dynamic gateway) runs
here against stub schedulers; the full process-worker flood lives in
``make smoke-autoscale``.
"""

from __future__ import annotations

import json
import time

import pytest

from distilp_tpu.control import (
    Action,
    ControlLoop,
    Controller,
    ControlPolicy,
    actions_to_jsonl,
)
from distilp_tpu.gateway import Gateway
from distilp_tpu.gateway.traces import make_fleet_from_spec
from distilp_tpu.obs import (
    FlightRecorder,
    SignalsPayload,
    SLOConfig,
    Timeline,
)
from distilp_tpu.obs.slo import SLOBurnSignal, WorkerSignal
from distilp_tpu.sched.metrics import METRIC_REGISTRY, SchedulerMetrics

TRACES = "tests/traces"


def sig(
    depth: float = 0.0,
    n_workers: int = 1,
    page: bool = False,
    alerts_open: int = 0,
    headroom: float | None = None,
    capacity: float | None = None,
    mem: float | None = None,
    trend: float | None = None,
    recovery: dict | None = None,
) -> SignalsPayload:
    """A synthetic /signals payload: depth spread evenly over workers."""
    slos = []
    if page:
        slos.append(
            SLOBurnSignal(
                slo="lat", budget=0.05, burn={}, firing=["page"]
            )
        )
        alerts_open = max(alerts_open, 1)
    return SignalsPayload(
        workers=[
            WorkerSignal(
                worker=i,
                queue_depth=depth / n_workers,
                queue_depth_trend_per_s=trend,
            )
            for i in range(n_workers)
        ],
        queue_depth_total=depth,
        slos=slos,
        alerts_open=alerts_open,
        max_sustainable_eps=capacity,
        headroom_eps=headroom,
        mem_headroom_bytes=mem,
        recovery=recovery,
    )


def policy(**kw) -> ControlPolicy:
    base = dict(
        min_workers=1,
        max_workers=4,
        scale_cooldown_s=10.0,
        headroom_min_frac=None,
        depth_high_per_worker=8.0,
        calm_hold_s=5.0,
    )
    base.update(kw)
    return ControlPolicy(**base)


# -- policy document ---------------------------------------------------------


def test_policy_fixture_parses():
    p = ControlPolicy.from_json(f"{TRACES}/control_policy.json")
    assert p.version == 1
    assert (p.min_workers, p.max_workers) == (2, 4)
    assert p.depth_high_per_worker == 8.0


def test_policy_rejects_unknown_fields_and_versions():
    with pytest.raises(Exception):
        ControlPolicy(version=2)
    with pytest.raises(Exception):
        ControlPolicy(scale_up_aggressiveness=11)  # not in the vocabulary
    with pytest.raises(Exception):
        Action(t=0.0, kind="reboot_everything", reason="nope")


def test_action_counters_are_registered():
    # DLP019's promise, asserted directly: every counter the controller
    # can increment is a documented METRIC_REGISTRY entry.
    for name in (
        "control_actions",
        "control_scale_out",
        "control_scale_in",
        "control_degrade_on",
        "control_degrade_off",
        "control_spec_k",
        "control_hold",
        "control_errors",
    ):
        assert name in METRIC_REGISTRY


# -- decide: bands, hysteresis, cooldown -------------------------------------


def test_depth_vote_scales_out_and_cooldown_suppresses():
    ctl = Controller(policy())
    acts = ctl.decide(sig(depth=16.0), now=0.0, n_workers=1)
    assert [a.kind for a in acts] == ["scale_out"]
    assert acts[0].target_workers == 2
    # Still hot 1s later: the cooldown holds the second spawn back.
    assert ctl.decide(sig(depth=16.0, n_workers=2), 1.0, 2) == []
    assert ctl._holds == 1
    # Cooldown expired: the standing vote trips again.
    acts = ctl.decide(sig(depth=32.0, n_workers=2), 10.0, 2)
    assert [a.kind for a in acts] == ["scale_out"]
    assert acts[0].target_workers == 3


def test_max_workers_clamps_scale_out():
    ctl = Controller(policy(max_workers=2))
    assert ctl.decide(sig(depth=99.0, n_workers=2), 0.0, 2) == []
    assert ctl._holds == 1


def test_page_alert_votes_and_degrades():
    ctl = Controller(policy())
    acts = ctl.decide(sig(page=True), now=0.0, n_workers=1)
    # Degrade is instant (bridges the spawn); both levers fire together.
    assert [a.kind for a in acts] == ["degrade_on", "scale_out"]
    # The page staying open does NOT re-fire degrade_on (edge-triggered).
    assert ctl.decide(sig(page=True, n_workers=2), 1.0, 2) == []
    acts = ctl.decide(sig(), now=2.0, n_workers=2)
    assert [a.kind for a in acts] == ["degrade_off"]


def test_headroom_floor_votes():
    p = policy(headroom_min_frac=0.10, depth_high_per_worker=None)
    ctl = Controller(p)
    # 5 eps headroom of 100 eps capacity: below the 10% floor.
    acts = ctl.decide(sig(headroom=5.0, capacity=100.0), 0.0, 1)
    assert [a.kind for a in acts] == ["scale_out"]
    assert "headroom" in acts[0].reason
    # Plenty of headroom: no vote (and calm scale-in needs n > min).
    ctl2 = Controller(p)
    assert ctl2.decide(sig(headroom=50.0, capacity=100.0), 0.0, 1) == []


def test_trend_vote():
    ctl = Controller(
        policy(depth_high_per_worker=None, trend_up_per_s=2.0)
    )
    acts = ctl.decide(sig(depth=1.0, trend=3.5), now=0.0, n_workers=1)
    assert [a.kind for a in acts] == ["scale_out"]
    assert "trending" in acts[0].reason


def test_scale_in_requires_sustained_calm():
    ctl = Controller(policy(calm_hold_s=5.0, scale_cooldown_s=0.0))
    # Calm at t=0 starts the timer; calm at t=4.9 is not held long
    # enough; a depth blip at t=5 RESETS it; only 5s of re-held calm
    # finally retires a worker.
    assert ctl.decide(sig(depth=0.0, n_workers=2), 0.0, 2) == []
    assert ctl.decide(sig(depth=0.0, n_workers=2), 4.9, 2) == []
    assert ctl.decide(sig(depth=30.0, n_workers=2), 5.0, 2) != []  # blip
    assert ctl.decide(sig(depth=0.0, n_workers=3), 6.0, 3) == []
    assert ctl.decide(sig(depth=0.0, n_workers=3), 10.0, 3) == []
    acts = ctl.decide(sig(depth=0.0, n_workers=3), 11.0, 3)
    assert [a.kind for a in acts] == ["scale_in"]
    assert acts[0].target_workers == 2


def test_scale_in_stops_at_min_workers():
    ctl = Controller(policy(min_workers=1, calm_hold_s=0.0))
    for t in (0.0, 1.0, 2.0):
        assert ctl.decide(sig(depth=0.0), now=t, n_workers=1) == []


def test_open_alert_blocks_scale_in():
    ctl = Controller(policy(calm_hold_s=0.0, scale_cooldown_s=0.0))
    ctl.decide(sig(alerts_open=1, n_workers=2), 0.0, 2)
    for t in (1.0, 20.0):
        acts = ctl.decide(sig(alerts_open=1, n_workers=2), t, 2)
        assert all(a.kind != "scale_in" for a in acts)


def test_spec_k_memory_lever_hysteresis():
    ctl = Controller(
        policy(mem_low_bytes=1e9, spec_k_low=1, spec_k_normal=4)
    )
    acts = ctl.decide(sig(mem=0.5e9), now=0.0, n_workers=1)
    assert [(a.kind, a.spec_k) for a in acts] == [("spec_k", 1)]
    # Still squeezed: no re-fire. Recovered: restore once.
    assert ctl.decide(sig(mem=0.6e9), 1.0, 1) == []
    acts = ctl.decide(sig(mem=2e9), now=2.0, n_workers=1)
    assert [(a.kind, a.spec_k) for a in acts] == [("spec_k", 4)]
    assert ctl.decide(sig(mem=2e9), 3.0, 1) == []


# -- step: the accounting contract -------------------------------------------


def test_step_counts_and_flight_records_every_action():
    metrics = SchedulerMetrics()
    flight = FlightRecorder(capacity=16)
    ctl = Controller(policy())
    acts = ctl.step(
        sig(page=True), now=3.0, n_workers=1, metrics=metrics,
        flight=flight,
    )
    assert [a.kind for a in acts] == ["degrade_on", "scale_out"]
    c = metrics.counters
    assert c["control_actions"] == 2
    assert c["control_degrade_on"] == 1
    assert c["control_scale_out"] == 1
    recs = flight.snapshot("control")
    assert len(recs) == 2
    for rec, act in zip(recs, acts):
        assert rec["t"] == 3.0
        assert rec["action"] == act.model_dump()
        # The justification rides the record: the signals snapshot.
        assert rec["signals"]["queue_depth_total"] == 0.0
        assert rec["signals"]["alerts_open"] == 1
    # A held decision is counted too (cooldown suppression).
    ctl.step(
        sig(page=True, n_workers=2), now=4.0, n_workers=2,
        metrics=metrics, flight=flight,
    )
    assert c["control_hold"] == 1
    assert c["control_actions"] == 2  # unchanged: nothing acted


# -- replay: the offline purity contract -------------------------------------


def test_replay_reproduces_committed_fixture_bytes():
    tl = Timeline.load(f"{TRACES}/slo_timeline_overload.jsonl")
    pol = ControlPolicy.from_json(f"{TRACES}/control_policy.json")
    cfg = SLOConfig.from_json(f"{TRACES}/slo_overload_spec.json")
    actions = Controller.replay(tl, pol, slo_config=cfg, step_s=0.5)
    committed = open(f"{TRACES}/control_expected_actions.jsonl").read()
    assert actions_to_jsonl(actions) == committed
    # Pure: a second replay of the same inputs is byte-identical.
    again = Controller.replay(tl, pol, slo_config=cfg, step_s=0.5)
    assert actions_to_jsonl(again) == committed


def test_replay_follows_its_own_scale_actions():
    tl = Timeline.load(f"{TRACES}/slo_timeline_overload.jsonl")
    pol = ControlPolicy.from_json(f"{TRACES}/control_policy.json")
    cfg = SLOConfig.from_json(f"{TRACES}/slo_overload_spec.json")
    actions = Controller.replay(tl, pol, slo_config=cfg, step_s=0.5)
    scale = [a for a in actions if a.kind in ("scale_out", "scale_in")]
    assert scale, "fixture must exercise the scale path"
    # target_workers walks one step at a time from the inferred start,
    # never outside the policy band.
    n = None
    for a in scale:
        if n is not None:
            assert abs(a.target_workers - n) == 1
        assert pol.min_workers <= a.target_workers <= pol.max_workers
        n = a.target_workers


def test_replay_rejects_bad_step_and_empty_timeline():
    with pytest.raises(ValueError):
        Controller.replay(Timeline(), ControlPolicy(), step_s=0.0)
    assert Controller.replay(Timeline(), ControlPolicy()) == []


def test_actions_to_jsonl_is_key_sorted():
    a = Action(t=1.5, kind="scale_out", target_workers=2, reason="r")
    line = actions_to_jsonl([a]).splitlines()[0]
    keys = list(json.loads(line))
    assert keys == sorted(keys)


# -- the live loop against a (stub) dynamic gateway --------------------------


def _control_gateway() -> Gateway:
    gw = Gateway(
        n_workers=1,
        scheduler_factory="tests.procstub:make_scheduler",
        dynamic=True,
        flight=FlightRecorder(capacity=64),
    )
    for i in range(4):
        fid = f"c{i:02d}"
        gw.register_fleet(
            fid, make_fleet_from_spec(fid, {"m": 3, "seed": 900 + i}), "stub"
        )
    return gw


def test_control_loop_actuates_scale_out_and_back():
    gw = _control_gateway()
    try:
        tl = Timeline()
        gw.attach_slo(None, tl)
        loop = ControlLoop(
            gw,
            Controller(
                ControlPolicy(
                    min_workers=1,
                    max_workers=2,
                    scale_cooldown_s=0.0,
                    headroom_min_frac=None,
                    depth_high_per_worker=8.0,
                    calm_hold_s=4.0,
                )
            ),
        )
        # Hot: the recorded depth trips the per-worker band -> spawn.
        tl.record("queue_depth.w0", 10.0, 16.0)
        acts = loop.step(now=10.0)
        assert [a.kind for a in acts] == ["scale_out"]
        assert gw.live_worker_ids() == [0, 1]
        # The fleet keeps serving through and after the actuation.
        for fid in sorted(gw._fleet_key):
            assert gw.handle_event(fid, "post-spawn")["seq"] == 1
        # Calm, held past calm_hold_s: retire back down to one.
        for t in (20.0, 22.0, 25.0):
            tl.record_many(t, {"queue_depth.w0": 0.0, "queue_depth.w1": 0.0})
            acts = loop.step(now=t)
        assert [a.kind for a in acts] == ["scale_in"]
        assert gw.live_worker_ids() == [0]
        for fid in sorted(gw._fleet_key):
            assert gw.handle_event(fid, "post-retire")["seq"] == 2

        # Reconciliation: counters == live trail == flight ring.
        c = gw.metrics.snapshot()["counters"]
        assert c["control_actions"] == len(loop.actions) == 2
        assert c["control_scale_out"] == c["workers_spawned"] == 1
        assert c["control_scale_in"] == c["workers_retired"] == 1
        recs = gw.flight.snapshot("control")
        assert [r["action"]["kind"] for r in recs] == [
            a.kind for a in loop.actions
        ]
        assert all("signals" in r for r in recs)
        # Every control tick publishes the worker count on the timeline.
        assert "control.workers" in tl.names()
        assert tl.latest("control.workers")[1] == 1.0
        assert loop.errors == 0
    finally:
        gw.close()


def test_control_loop_survives_actuation_failure():
    gw = _control_gateway()
    try:
        tl = Timeline()
        gw.attach_slo(None, tl)
        ctl = Controller(
            ControlPolicy(
                min_workers=1,
                max_workers=2,
                scale_cooldown_s=0.0,
                headroom_min_frac=None,
                depth_high_per_worker=8.0,
            )
        )
        loop = ControlLoop(gw, ctl, period_s=0.01)
        gw.spawn_worker = None  # actuation will raise TypeError
        tl.record("queue_depth.w0", 1.0, 50.0)
        # step() raising is the unit surface ...
        with pytest.raises(TypeError):
            loop.step(now=1.0)
        # ... and the threaded runner counts it and keeps going.
        loop.start()
        deadline = time.time() + 10.0
        while loop.errors < 2 and time.time() < deadline:
            time.sleep(0.01)
        loop.stop()
        assert loop.errors >= 2  # it survived the first failure
        counters = gw.metrics.snapshot()["counters"]
        assert counters["control_errors"] == loop.errors
        # Topology untouched throughout; serving still works.
        assert gw.live_worker_ids() == [0]
        assert gw.handle_event(sorted(gw._fleet_key)[0], "ev")["seq"] == 1
    finally:
        gw.close()


def test_control_loop_noops_without_timeline():
    gw = _control_gateway()
    try:
        loop = ControlLoop(gw, Controller(ControlPolicy()))
        assert loop.step(now=0.0) == []
        assert loop.actions == []
    finally:
        gw.close()


# -- quarantine vote (ISSUE 20 crash-loop breaker -> scale-out) ---------------


def test_quarantine_vote_fires_on_increase_only():
    """The crash-loop breaker's quarantine is permanent capacity loss:
    the controller votes scale-out on the INCREASE of
    ``recovery.workers_quarantined`` — once per newly opened breaker,
    never again for the same high-water mark."""
    c = Controller(policy())
    # Supervised but nothing quarantined: no vote.
    assert c.decide(sig(recovery={"workers_quarantined": 0}), 0.0, 2) == []
    # A breaker opens: one scale-out vote.
    acts = c.decide(sig(recovery={"workers_quarantined": 1}), 1.0, 2)
    assert [a.kind for a in acts] == ["scale_out"]
    assert acts[0].target_workers == 3
    assert "quarantined" in acts[0].reason
    # Same count re-observed past the cooldown: high-water mark holds,
    # no re-vote (the lost worker was already compensated for).
    assert c.decide(sig(recovery={"workers_quarantined": 1}), 20.0, 3) == []
    # A SECOND breaker opens: fires again (delta 1, past cooldown).
    acts = c.decide(sig(recovery={"workers_quarantined": 2}), 40.0, 3)
    assert [a.kind for a in acts] == ["scale_out"]


def test_quarantine_vote_respects_gates():
    """The vote is inert without a recovery block (unsupervised
    gateway), when the policy knob is off, and — like every vote — it
    cannot breach max_workers."""
    # No recovery block: nothing to vote on.
    c = Controller(policy())
    assert c.decide(sig(), 0.0, 2) == []
    # Knob off: quarantines observed but never voted on.
    c = Controller(policy(scale_out_on_quarantine=False))
    assert c.decide(sig(recovery={"workers_quarantined": 1}), 0.0, 2) == []
    # At the ceiling: the vote holds instead of acting.
    c = Controller(policy(max_workers=2))
    assert c.decide(sig(recovery={"workers_quarantined": 1}), 0.0, 2) == []
    assert c._holds == 1

"""Observability: span tracing, exporters, Prometheus exposition, flight
recorder, and the metric registry.

Pure-plumbing tests (tracer, exporters, registry) run without the solver;
integration tests reuse the L=32 model + M=4 synthetic fleets and the
[4, 8] k-grid of tests/test_sched.py so jit programs are shared across
modules and each post-compile tick is milliseconds.
"""

from __future__ import annotations

import asyncio
import json
import threading

import pytest

from distilp_tpu.obs import (
    NOOP_TRACER,
    FlightRecorder,
    JsonlSpanWriter,
    Tracer,
    now_ms,
    parse_prometheus_text,
    read_spans,
    render_prometheus,
    spans_to_chrome,
    top_spans,
)
from distilp_tpu.sched import (
    DeviceDegrade,
    FaultPlan,
    LoadTick,
    Scheduler,
    chaos_replay,
    generate_trace,
    registry_help,
    replay,
)
from distilp_tpu.sched.metrics import FAULT_COUNTERS, METRIC_REGISTRY
from distilp_tpu.utils import make_synthetic_fleet

GAP = 1e-3
KS = [4, 8]  # proper factors of L=32


@pytest.fixture(scope="module")
def model():
    from distilp_tpu.profiler.api import profile_model

    return profile_model(
        "tests/configs/llama31_8b_4bit.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()


@pytest.fixture()
def fleet():
    return make_synthetic_fleet(4, seed=11)


def make_scheduler(fleet, model, **kw):
    kw.setdefault("mip_gap", GAP)
    kw.setdefault("kv_bits", "4bit")
    kw.setdefault("backend", "jax")
    kw.setdefault("k_candidates", KS)
    return Scheduler([d.model_copy(deep=True) for d in fleet], model, **kw)


def by_trace(spans):
    out = {}
    for s in spans:
        out.setdefault(s["trace_id"], []).append(s)
    return out


def roots_of(trace_spans):
    return [s for s in trace_spans if s["parent_id"] is None]


# -- the tracer core (no solver) -------------------------------------------


def test_tracer_nesting_ring_and_json():
    t = Tracer(capacity=8)
    with t.span("outer", attrs={"kind": "load"}) as outer:
        outer.add_event("decision", reason="because")
        with t.span("inner") as inner:
            assert t.current() == inner.context()
        assert t.current() == outer.context()
    assert t.current() is None
    spans = t.spans()
    assert [s["name"] for s in spans] == ["inner", "outer"]
    inner_rec, outer_rec = spans
    assert inner_rec["trace_id"] == outer_rec["trace_id"]
    assert inner_rec["parent_id"] == outer_rec["span_id"]
    assert outer_rec["parent_id"] is None
    assert outer_rec["attrs"]["kind"] == "load"
    assert outer_rec["events"][0]["name"] == "decision"
    assert outer_rec["dur_ms"] >= inner_rec["dur_ms"] >= 0.0
    json.dumps(spans)  # every record is wire-ready

    # The ring is bounded: old spans fall off, nothing errors.
    for i in range(20):
        with t.span(f"s{i}"):
            pass
    assert len(t.spans()) == 8
    # drain() empties it.
    assert len(t.drain()) == 8
    assert t.spans() == []


def test_attr_values_are_coerced_json_safe():
    import numpy as np

    t = Tracer()
    with t.span("s", attrs={"np": np.int64(3), "obj": object()}) as s:
        s.set_attr("f32", np.float32(1.5))
    rec = t.spans()[0]
    assert rec["attrs"]["np"] == 3.0
    assert rec["attrs"]["f32"] == 1.5
    assert isinstance(rec["attrs"]["obj"], str)
    json.dumps(rec)


def test_cross_thread_attach_parents_correctly():
    """The worker-adoption idiom: a foreign context attached on another
    thread parents that thread's spans (and the after-the-fact queue-wait
    record) under the original root."""
    t = Tracer()
    root = t.start_span("ingest", parent=None)
    t_enq = now_ms()

    def worker():
        t.record_span("queue_wait", t_enq, parent=root.context())
        with t.attach(root.context()):
            with t.span("tick"):
                pass

    th = threading.Thread(target=worker)
    th.start()
    th.join()
    root.end()
    spans = {s["name"]: s for s in t.spans()}
    assert spans["queue_wait"]["parent_id"] == root.span_id
    assert spans["tick"]["parent_id"] == root.span_id
    assert spans["tick"]["trace_id"] == spans["ingest"]["trace_id"]
    assert spans["tick"]["thread"] != spans["ingest"]["thread"]


def test_noop_tracer_is_inert():
    s = NOOP_TRACER.span("x")
    with s:
        s.add_event("y")
        s.set_attr("a", 1)
    assert s.context() is None
    assert NOOP_TRACER.current() is None
    assert NOOP_TRACER.record_span("q", 0.0) is None
    assert NOOP_TRACER.spans() == [] and NOOP_TRACER.drain() == []
    assert NOOP_TRACER.enabled is False


def test_jsonl_writer_roundtrip(tmp_path):
    path = tmp_path / "spans.jsonl"
    t = Tracer(writer=JsonlSpanWriter(path))
    with t.span("a"):
        with t.span("b"):
            pass
    t.close()
    back = read_spans(path)
    assert [s["name"] for s in back] == ["b", "a"]
    assert back == t.spans()


# -- Chrome trace conversion ------------------------------------------------


def _synthetic_trace_spans():
    """A hand-built ingest->route/queue_wait->tick tree on two threads."""
    t = Tracer()
    root = t.start_span("gateway.ingest", parent=None, attrs={"fleet": "f0"})
    t.record_span("gateway.route", now_ms(), parent=root.context())
    t_enq = now_ms()

    def worker():
        t.record_span(
            "gateway.queue_wait", t_enq, parent=root.context(),
            attrs={"worker": 0},
        )
        with t.attach(root.context()):
            with t.span("sched.tick") as tick:
                tick.add_event("health", state="degraded")

    th = threading.Thread(target=worker, name="gw-worker-0")
    th.start()
    th.join()
    root.end()
    return t.spans()


def test_chrome_conversion_schema_and_flows():
    spans = _synthetic_trace_spans()
    chrome = spans_to_chrome(spans)
    # Loads as the Chrome trace-event JSON object form.
    doc = json.loads(json.dumps(chrome))
    events = doc["traceEvents"]
    assert isinstance(events, list) and events

    phases = {}
    for ev in events:
        assert {"name", "ph", "pid", "tid"} <= set(ev)
        if ev["ph"] != "M":
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        phases.setdefault(ev["ph"], []).append(ev)
    # One complete event per span, each on a named thread track.
    assert len(phases["X"]) == len(spans)
    for ev in phases["X"]:
        assert ev["dur"] >= 0 and ev["args"]["trace_id"]
    names = {m["args"]["name"] for m in phases["M"]}
    assert "gw-worker-0" in names
    # The queue wait became a flow arrow: an s/f pair sharing an id, the
    # start on the enqueuing thread, the finish on the worker track.
    assert len(phases["s"]) == 1 and len(phases["f"]) == 1
    s_ev, f_ev = phases["s"][0], phases["f"][0]
    assert s_ev["id"] == f_ev["id"]
    assert s_ev["tid"] != f_ev["tid"]
    # Span events became instants.
    assert any(ev["name"] == "health" for ev in phases.get("i", []))


def test_top_spans_orders_by_duration():
    spans = [
        {"name": "a", "dur_ms": 1.0},
        {"name": "b", "dur_ms": 9.0},
        {"name": "c", "dur_ms": 5.0},
    ]
    assert [s["name"] for s in top_spans(spans, 2)] == ["b", "c"]


def test_spans_cli_roundtrip(tmp_path):
    from distilp_tpu.cli.solver_cli import main as cli_main

    path = tmp_path / "spans.jsonl"
    with open(path, "w") as fh:
        for s in _synthetic_trace_spans():
            fh.write(json.dumps(s) + "\n")
    out = tmp_path / "chrome.json"
    rc = cli_main(["spans", str(tmp_path), "--out", str(out), "--top", "2"])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["traceEvents"]
    # Empty/missing inputs are errors, not empty files.
    assert cli_main(["spans", str(tmp_path / "nope.jsonl")]) == 2


# -- metric registry + Prometheus exposition --------------------------------


def _registered(sample_name: str):
    assert sample_name.startswith("distilp_")
    name = sample_name[len("distilp_"):]
    help_txt = registry_help(name)
    if help_txt is None:
        for suffix in ("_sum", "_count"):
            if name.endswith(suffix):
                help_txt = registry_help(name[: -len(suffix)])
    return help_txt


def test_fault_counters_all_registered():
    for name in FAULT_COUNTERS:
        assert name in METRIC_REGISTRY, name
    # Families resolve dynamic names; unknown names stay unresolved.
    assert registry_help("tick_cold") and registry_help("fault_injected_nan_poison")
    assert registry_help("no_such_counter_xyz") is None


def test_render_parse_roundtrip_two_shards():
    shards = [
        {
            "fleet": "f000", "shard": "f000::default", "worker": 0,
            "health": "healthy",
            "counters": {"events_total": 5, "tick_warm": 4, "tick_cold": 1},
            "latency": {
                "event_to_placement": {
                    "count": 5, "mean_ms": 10.0, "window_count": 5,
                    "window_mean_ms": 10.0, "p50_ms": 9.0, "p99_ms": 30.0,
                    "max_ms": 30.0,
                }
            },
        },
        {
            "fleet": "f001", "shard": "f001::default", "worker": 1,
            "health": "degraded",
            "counters": {"events_total": 7, "events_quarantined": 1},
            "latency": {},
        },
    ]
    text = render_prometheus(
        shards,
        gateway_counters={"gateway_events": 12, "worker_0_events": 5,
                          "worker_1_events": 7},
        gateway_latency={},
    )
    parsed = parse_prometheus_text(text)
    assert parsed["samples"], "no samples rendered"
    # Every sample line resolves to a registered name (summary suffixes
    # resolve through their base metric).
    for name, labels, value in parsed["samples"]:
        assert _registered(name), f"unregistered sample {name}"
    # HELP + TYPE present for every metric family that has samples.
    base_names = set(parsed["help"])
    assert base_names == set(parsed["type"])
    for name, labels, value in parsed["samples"]:
        base = name
        if base not in base_names:
            base = base.rsplit("_", 1)[0]  # _sum/_count
        assert base in base_names, name
    # Per-fleet labels distinguish the two shards.
    ev_samples = [
        (labels, value)
        for name, labels, value in parsed["samples"]
        if name == "distilp_events_total"
    ]
    fleets = {labels["fleet"]: value for labels, value in ev_samples}
    assert fleets == {"f000": 5.0, "f001": 7.0}
    for labels, _ in ev_samples:
        # Health is deliberately NOT on counter series (a transition would
        # churn every series identity); it lives on the health gauge.
        assert set(labels) == {"fleet", "shard", "worker"}
    # worker_<i>_events folded into one labeled metric.
    wk = {
        labels["worker"]: value
        for name, labels, value in parsed["samples"]
        if name == "distilp_worker_events"
    }
    assert wk == {"0": 5.0, "1": 7.0}
    # The summary carries quantiles + sum/count.
    q = {
        labels.get("quantile"): value
        for name, labels, value in parsed["samples"]
        if name == "distilp_event_to_placement" and "quantile" in labels
    }
    assert q == {"0.5": 9.0, "0.99": 30.0}
    counts = [
        value
        for name, _, value in parsed["samples"]
        if name == "distilp_event_to_placement_count"
    ]
    assert counts == [5.0]
    # Health gauge present and typed; the state string rides THIS metric's
    # label, value = rank.
    assert parsed["type"]["distilp_health_state"] == "gauge"
    health = {
        labels["fleet"]: (labels["health"], value)
        for name, labels, value in parsed["samples"]
        if name == "distilp_health_state"
    }
    assert health == {"f000": ("healthy", 0.0), "f001": ("degraded", 1.0)}
    # Exact-sum passthrough: a snapshot carrying total_ms wins over the
    # rounded-mean reconstruction (monotonicity of the summary _sum).
    assert parse_prometheus_text(
        render_prometheus(
            [
                {
                    "fleet": "fz", "shard": "fz::d", "worker": 0,
                    "health": "healthy", "counters": {},
                    "latency": {
                        "event_to_placement": {
                            "count": 3, "total_ms": 10.001, "mean_ms": 3.334,
                            "p50_ms": 3.0, "p99_ms": 4.0, "max_ms": 4.0,
                        }
                    },
                }
            ]
        )
    )["samples"]
    # Escape round trip: backslash+n must survive as two characters.
    tricky = render_prometheus(
        [
            {
                "fleet": "a\\nightly", "shard": "s", "worker": 0,
                "health": "healthy", "counters": {"events_total": 1},
                "latency": {},
            }
        ]
    )
    got = [
        labels["fleet"]
        for name, labels, _v in parse_prometheus_text(tricky)["samples"]
        if name == "distilp_events_total"
    ]
    assert got == ["a\\nightly"]


def test_registry_covers_live_scheduler_counters(fleet, model):
    """Replay a churn trace and check every counter the scheduler actually
    emitted resolves through the registry — the drift test DLP019 cannot
    do for f-string names."""
    sched = make_scheduler(fleet, model)
    trace = generate_trace("mixed", 12, seed=23, base_fleet=fleet)
    replay(sched, trace)
    for name in sched.metrics.counters:
        assert registry_help(name), f"counter {name!r} not covered"
    for name in sched.metrics.hists:
        assert registry_help(name), f"hist {name!r} not covered"


# -- flight recorder --------------------------------------------------------


def test_flight_recorder_ring_and_dump(tmp_path):
    fr = FlightRecorder(capacity=3, dump_dir=tmp_path)
    for i in range(5):
        fr.record("f0", {"seq": i})
    assert [r["seq"] for r in fr.snapshot("f0")] == [2, 3, 4]
    assert fr.snapshot("ghost") == []
    path = fr.trigger("f0", "breaker_open", {"seq": 4})
    assert path is not None and path.exists()
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    header, records = lines[0], lines[1:]
    assert header["reason"] == "breaker_open"
    assert header["flight"] == "f0"
    assert header["trigger"] == {"seq": 4}
    assert header["records"] == 3 == len(records)
    # The trigger also lands in the live ring as a marker.
    assert any("flight_trigger" in r for r in fr.snapshot("f0"))
    assert fr.dumps == [path]


def test_flight_without_dump_dir_marks_ring_only():
    fr = FlightRecorder(capacity=4)
    fr.record("x", {"seq": 0})
    assert fr.trigger("x", "chaos_violation") is None
    assert fr.snapshot("x")[-1]["flight_trigger"] == "chaos_violation"
    assert fr.dumps == []


# -- scheduler integration --------------------------------------------------


def test_scheduler_tick_span_tree_and_quarantine(fleet, model):
    tracer = Tracer()
    sched = make_scheduler(fleet, model, tracer=tracer)
    sched.handle(LoadTick(t_comm_jitter={}))
    sched.handle(DeviceDegrade(name=fleet[1].name, t_comm_scale=1.1))
    # A poisoned event: quarantined, no solve.
    sched.handle(DeviceDegrade(name=fleet[1].name, t_comm_scale=float("nan")))
    traces = by_trace(tracer.spans())
    assert len(traces) == 3  # one rooted trace per handled event
    solved, quarantined = 0, 0
    for spans in traces.values():
        roots = roots_of(spans)
        assert len(roots) == 1 and roots[0]["name"] == "sched.tick"
        ids = {s["span_id"] for s in spans}
        assert all(
            s["parent_id"] in ids for s in spans if s["parent_id"] is not None
        ), "orphan span"
        names = {s["name"] for s in spans}
        if "sched.solve" in names:
            solved += 1
            assert "sched.publish" in names
            solve = next(s for s in spans if s["name"] == "sched.solve")
            assert solve["parent_id"] == roots[0]["span_id"]
            # The solver's timings dict rode the solve span.
            assert "solve_ms" in solve["attrs"]
            assert solve["attrs"]["lp_backend"] in ("ipm", "pdhg")
        else:
            quarantined += 1
            events = [e["name"] for e in roots[0]["events"]]
            assert "quarantined" in events
            # The quarantined event re-served the previous view: the tick
            # span carries the mode of what was actually served.
            assert roots[0]["attrs"]["mode"] == "warm"
    assert solved == 2 and quarantined == 1
    # Tick spans carry the served mode (cold boot, warm drift, re-served).
    modes = sorted(
        roots_of(spans)[0]["attrs"]["mode"] for spans in traces.values()
    )
    assert modes == ["cold", "warm", "warm"]
    # Direct library users get the same breakdown off the replanner — the
    # timings the solve span carries are also the planner's attribute.
    (_key, planner), = sched.pool.items()
    assert planner.last_tick_timings.get("lp_backend") in ("ipm", "pdhg")
    assert "solve_ms" in planner.last_tick_timings


def test_untraced_scheduler_counters_identical(fleet, model):
    """The byte-identical contract: same trace with and without a tracer
    (and with a flight recorder) produces the same counters and the same
    placements."""
    trace = generate_trace("mixed", 10, seed=5, base_fleet=fleet)
    plain = make_scheduler(fleet, model)
    r1 = replay(plain, trace)
    fr = FlightRecorder(capacity=64)
    traced = make_scheduler(
        fleet, model, tracer=Tracer(), flight=fr, flight_key="f"
    )
    r2 = replay(traced, trace)
    assert plain.metrics.counters == traced.metrics.counters
    assert [
        (v.result.k, tuple(v.result.w), v.result.obj_value) for v in r1.views
    ] == [
        (v.result.k, tuple(v.result.w), v.result.obj_value) for v in r2.views
    ]
    assert len(fr.snapshot("f")) == len(trace)


def test_flight_breaker_postmortem_reconciles_with_chaos(fleet, model, tmp_path):
    """The chaos acceptance: the soak under the BUNDLED fault plan (the
    `make smoke-chaos` plan, whose consecutive solver exceptions at ticks
    7-8 open the breaker) produces a post-mortem dump whose records
    reconcile with the ChaosReport — and the breaker-open tick is IN the
    dump, span id attached."""
    from distilp_tpu.sched import read_trace

    tracer = Tracer()
    fr = FlightRecorder(capacity=512, dump_dir=tmp_path)
    sched = make_scheduler(
        fleet, model,
        max_retries=1, retry_backoff_s=0.001,
        breaker_threshold=2, breaker_cooldown=1, healthy_after=2,
        tracer=tracer, flight=fr, flight_key="default",
    )
    trace = read_trace("tests/traces/scheduler_smoke_20.jsonl")
    plan = FaultPlan.from_json("tests/traces/chaos_plan.json")
    report = chaos_replay(sched, trace, plan)
    assert report.violations(model.L) == []
    assert sched.metrics.counters["breaker_open"] == 1
    assert sched.metrics.counters["flight_dumps"] == 1

    assert len(fr.dumps) == 1
    lines = [json.loads(ln) for ln in fr.dumps[0].read_text().splitlines()]
    header, records = lines[0], lines[1:]
    assert header["reason"] == "breaker_open"
    # The triggering record is the breaker-open tick: broken health, a
    # breaker_open counter delta, and the tick's span ids (tracing on).
    trig = header["trigger"]
    assert trig["health"] == "broken"
    assert trig["counters_delta"].get("breaker_open") == 1
    assert trig["span_id"] and trig["trace_id"]
    assert records[-1] == trig  # the dump INCLUDES the breaker-open tick
    # That span id is a real recorded sched.tick span.
    tick_spans = {
        s["span_id"]: s for s in tracer.spans() if s["name"] == "sched.tick"
    }
    assert trig["span_id"] in tick_spans
    assert any(
        e["name"] == "breaker_open"
        for e in tick_spans[trig["span_id"]]["events"]
    )

    # Ring records reconcile with the ChaosReport: one record per handled
    # event (trigger markers excluded), quarantine deltas sum to the
    # report's quarantine count.
    ring = fr.snapshot("default")
    tick_recs = [r for r in ring if "flight_trigger" not in r]
    assert len(tick_recs) == len(report.records)
    quarantined_delta = sum(
        r["counters_delta"].get("events_quarantined", 0) for r in tick_recs
    )
    assert quarantined_delta == report.summary()["quarantined"]
    assert quarantined_delta == sched.metrics.counters["events_quarantined"]


def test_jax_profile_dir_first_tick_smoke(fleet, model, tmp_path):
    """serve --jax-profile-dir satellite: the first cold solve runs under
    jax.profiler.trace and leaves a non-empty profile directory (CPU)."""
    profile_dir = tmp_path / "xla"
    sched = make_scheduler(fleet, model, jax_profile_dir=str(profile_dir))
    sched.handle(LoadTick(t_comm_jitter={}))
    files = [p for p in profile_dir.rglob("*") if p.is_file()]
    assert files, "profiler trace produced no files"
    # One capture only: the second tick must not re-enter the profiler.
    before = len(files)
    sched.handle(LoadTick(t_comm_jitter={}))
    after = len([p for p in profile_dir.rglob("*") if p.is_file()])
    assert after == before


# -- gateway integration ----------------------------------------------------


def _gateway_fleet(fleet_id: str, seed: int):
    from distilp_tpu.gateway.traces import make_fleet_from_spec

    return make_fleet_from_spec(fleet_id, {"m": 4, "seed": seed})


def test_gateway_concurrent_span_trees(model):
    """The acceptance gate: a concurrent multi-fleet async replay (3
    fleets, 2 workers) yields ONE rooted span tree per event —
    ingest -> {route, queue-wait, tick -> solve} — with no orphan spans,
    even with coroutines interleaving on the loop thread."""
    from distilp_tpu.gateway import Gateway

    tracer = Tracer(capacity=65536)
    gw = Gateway(
        n_workers=2,
        scheduler_kwargs=dict(
            mip_gap=GAP, kv_bits="4bit", backend="jax", k_candidates=KS
        ),
        tracer=tracer,
    )
    events_per_fleet = 3
    try:
        fleets = ["oa", "ob", "oc"]
        for i, fid in enumerate(fleets):
            gw.register_fleet(fid, _gateway_fleet(fid, 60 + i), model)

        async def drive(fid):
            for _ in range(events_per_fleet):
                await gw.handle_event_async(fid, LoadTick(t_comm_jitter={}))

        async def main():
            await asyncio.gather(*(drive(f) for f in fleets))

        asyncio.run(main())
    finally:
        gw.close()

    traces = by_trace(tracer.spans())
    assert len(traces) == len(fleets) * events_per_fleet
    for spans in traces.values():
        roots = roots_of(spans)
        assert len(roots) == 1, "multiple roots in one trace"
        root = roots[0]
        assert root["name"] == "gateway.ingest"
        ids = {s["span_id"] for s in spans}
        for s in spans:
            if s["parent_id"] is not None:
                assert s["parent_id"] in ids, f"orphan span {s['name']}"
        named = {}
        for s in spans:
            named.setdefault(s["name"], []).append(s)
        for required in (
            "gateway.route", "gateway.queue_wait", "sched.tick", "sched.solve"
        ):
            assert required in named, f"missing {required}"
        # Causal shape: route + queue-wait + tick under ingest, solve
        # under tick; the tick ran on a worker thread, the ingest on the
        # loop thread.
        assert named["gateway.queue_wait"][0]["parent_id"] == root["span_id"]
        tick = named["sched.tick"][0]
        assert tick["parent_id"] == root["span_id"]
        assert named["sched.solve"][0]["parent_id"] == tick["span_id"]
        assert tick["thread"].startswith("gw-worker-")
        assert tick["thread"] == named["gateway.queue_wait"][0]["thread"]
        # Ingest is the outermost timed region of its trace.
        assert root["dur_ms"] >= tick["dur_ms"]
    # Concurrency really happened across both workers.
    threads = {
        s["thread"] for s in tracer.spans() if s["name"] == "sched.tick"
    }
    assert len(threads) == 2
    # And the whole batch converts to loadable Chrome JSON.
    chrome = spans_to_chrome(tracer.spans())
    assert json.loads(json.dumps(chrome))["traceEvents"]


def test_gateway_http_prom_flight_and_tracing(model, tmp_path):
    """HTTP surface: /metrics content-negotiates Prometheus text (Accept
    or ?format=prom) while JSON stays the default; /debug/flight serves
    the live ring; a traced POST /events roots at http.request."""
    import urllib.error
    import urllib.request

    from distilp_tpu.gateway import Gateway, GatewayHTTPServer

    tracer = Tracer(capacity=65536)
    fr = FlightRecorder(capacity=32, dump_dir=tmp_path)
    gw = Gateway(
        n_workers=2,
        scheduler_kwargs=dict(
            mip_gap=GAP, kv_bits="4bit", backend="jax", k_candidates=KS
        ),
        tracer=tracer,
        flight=fr,
    )

    def get(port, path, accept=None):
        req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
        if accept:
            req.add_header("Accept", accept)
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return r.status, r.headers.get("Content-Type", ""), r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.headers.get("Content-Type", ""), e.read()

    def post(port, path, payload):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(payload).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())

    try:
        gw.register_fleet("hx", _gateway_fleet("hx", 77), model)
        gw.register_fleet("hy", _gateway_fleet("hy", 78), model)

        async def main():
            srv = GatewayHTTPServer(gw)
            await srv.start()
            loop = asyncio.get_running_loop()
            port = srv.port
            ev = {"kind": "load", "t_comm_jitter": {}}
            for fid in ("hx", "hy"):
                st, out = await loop.run_in_executor(
                    None, post, port, "/events", {"fleet": fid, "event": ev}
                )
                assert st == 200 and out["view"]["certified"]

            # Default /metrics stays the JSON snapshot.
            st, ctype, body = await loop.run_in_executor(
                None, get, port, "/metrics", None
            )
            assert st == 200 and ctype.startswith("application/json")
            assert json.loads(body)["shards"] == 2

            # Accept: text/plain negotiates the labeled exposition.
            st, ctype, body = await loop.run_in_executor(
                None, get, port, "/metrics", "text/plain"
            )
            assert st == 200 and ctype.startswith("text/plain")
            parsed = parse_prometheus_text(body.decode())
            for name, _labels, _v in parsed["samples"]:
                assert _registered(name), f"unregistered sample {name}"
            fleets = {
                labels["fleet"]
                for name, labels, _v in parsed["samples"]
                if name == "distilp_events_total"
            }
            assert fleets == {"hx", "hy"}  # labels distinguish the shards
            assert parsed["help"] and parsed["type"]

            # ?format=prom forces it without the header.
            st, ctype, body2 = await loop.run_in_executor(
                None, get, port, "/metrics?format=prom", None
            )
            assert st == 200 and ctype.startswith("text/plain")
            assert body2.decode().startswith("# HELP")

            # Live flight ring over HTTP; unknown fleet 404s.
            st, _ctype, body = await loop.run_in_executor(
                None, get, port, "/debug/flight/hx", None
            )
            assert st == 200
            flight = json.loads(body)
            assert flight["fleet"] == "hx"
            assert len(flight["records"]) == 1
            assert flight["records"][0]["mode"] == "cold"
            st, _ctype, _body = await loop.run_in_executor(
                None, get, port, "/debug/flight/ghost", None
            )
            assert st == 404
            await srv.close()

        asyncio.run(main())
    finally:
        gw.close()

    # Each traced POST rooted at http.request, ingest nested under it.
    traces = by_trace(
        [s for s in tracer.spans() if s["name"] != "gateway.route"]
    )
    http_traces = [
        spans
        for spans in traces.values()
        if any(s["name"] == "http.request" for s in spans)
    ]
    assert len(http_traces) == 2
    for spans in http_traces:
        roots = roots_of(spans)
        assert len(roots) == 1 and roots[0]["name"] == "http.request"
        ingest = next(s for s in spans if s["name"] == "gateway.ingest")
        assert ingest["parent_id"] == roots[0]["span_id"]


def test_worker_gauge_exposition_roundtrip():
    """Gauge round-trip pin (PR 12 added worker_gauges to the renderer
    but only counters/summaries had round-trip coverage): labeled per
    worker, multiple workers, zero-valued samples all survive the
    render -> parse trip with TYPE gauge and registry-backed HELP."""
    text = render_prometheus(
        [],
        gateway_counters={"gateway_events": 3},
        worker_gauges={
            "worker_queue_depth": {"0": 7, "1": 0, "2": 3.5},
        },
    )
    parsed = parse_prometheus_text(text)
    assert parsed["type"]["distilp_worker_queue_depth"] == "gauge"
    # HELP comes from the registry, never the "(unregistered)" fallback.
    assert "unregistered" not in parsed["help"]["distilp_worker_queue_depth"]
    assert _registered("distilp_worker_queue_depth")
    depths = {
        labels["worker"]: value
        for name, labels, value in parsed["samples"]
        if name == "distilp_worker_queue_depth"
    }
    # All three workers, the zero-valued one included (an idle worker
    # DISAPPEARING from the exposition would read as a dead scrape).
    assert depths == {"0": 7.0, "1": 0.0, "2": 3.5}
    # Multiple gauge names render independently.
    two = parse_prometheus_text(
        render_prometheus(
            [],
            worker_gauges={
                "worker_queue_depth": {"0": 0},
                "worker_events": {"0": 2},
            },
        )
    )
    assert two["type"]["distilp_worker_queue_depth"] == "gauge"
    assert ("distilp_worker_queue_depth", {"worker": "0"}, 0.0) in two[
        "samples"
    ]


def test_spans_stats_aggregation_and_cli(tmp_path):
    """`solver spans --stats`: per-span-name table (count, p50/p99, top
    slowest with trace ids) — the CI-log view of a span dir."""
    from distilp_tpu.cli.solver_cli import main as cli_main
    from distilp_tpu.obs import span_stats

    spans = _synthetic_trace_spans()
    rows = span_stats(spans, top=2)
    by_name = {r["name"]: r for r in rows}
    assert set(by_name) == {
        "gateway.ingest", "gateway.route", "gateway.queue_wait", "sched.tick",
    }
    tick = by_name["sched.tick"]
    assert tick["count"] == 1 and tick["p50_ms"] == tick["max_ms"]
    # Rows sort by total duration, descending: where the wall clock went.
    totals = [r["total_ms"] for r in rows]
    assert totals == sorted(totals, reverse=True)
    # Slowest instances carry their trace ids (the grep handle).
    assert all(s["trace_id"] for r in rows for s in r["slowest"])
    assert all(len(r["slowest"]) <= 2 for r in rows)
    path = tmp_path / "spans.jsonl"
    with open(path, "w") as fh:
        for s in spans:
            fh.write(json.dumps(s) + "\n")
    rc = cli_main(["spans", str(tmp_path), "--stats"])
    assert rc == 0
    # --stats alone converts nothing; with --out it still writes Chrome.
    assert not (tmp_path / "spans.chrome.json").exists()
    out = tmp_path / "c.json"
    assert cli_main(["spans", str(path), "--stats", "--out", str(out)]) == 0
    assert json.loads(out.read_text())["traceEvents"]


def test_scheduler_timeline_sample_real(fleet, model):
    """Scheduler.timeline_sample on a live scheduler: counters, latency
    quantiles, the serve clock and the health rank all present under
    the documented series names (the single-scheduler SLO input)."""
    sched = make_scheduler(fleet, model)
    try:
        for ev in generate_trace("drift", 2, seed=3, base_fleet=sched.fleet.device_list()):
            sched.handle(ev)
        sample = sched.timeline_sample()
        assert sample["c.events_total"] == 2.0
        assert sample["c.tick_cold"] + sample.get("c.tick_warm", 0) >= 1.0
        assert sample["last_serve_ms"] > 0.0
        assert sample["health"] == 0.0
        assert sample["lat.event_to_placement.count"] == 2.0
        assert sample["lat.event_to_placement.p99_ms"] > 0.0
        # No SLO knob engaged: sampling is pull-only, so the scheduler's
        # own counters contain no timeline/slo entries.
        counters = sched.metrics_snapshot()["counters"]
        assert not any(
            k.startswith(("timeline_", "slo_")) for k in counters
        )
    finally:
        sched.close()


# -- Prometheus parser edge cases (round-trip against the renderer) ---------


def test_prometheus_escaped_label_values_roundtrip():
    """Backslashes, quotes, and newlines in label values survive the
    render -> parse trip exactly — including the adversarial literal
    backslash-then-n, which a naive sequential-replace unescaper would
    corrupt into a newline."""
    evil = {
        "fleet": 'f"0\\n0',  # literal backslash + n, plus a quote
        "shard": "s\nhard",  # a REAL newline
        "worker": "0\\",  # trailing lone backslash
    }
    text = render_prometheus(
        [
            {
                **evil,
                "health": "healthy",
                "counters": {"events_total": 3},
                "latency": {},
            }
        ]
    )
    parsed = parse_prometheus_text(text)
    sample = next(
        (name, labels, v)
        for name, labels, v in parsed["samples"]
        if name == "distilp_events_total"
    )
    assert sample[1]["fleet"] == evil["fleet"]
    assert sample[1]["shard"] == evil["shard"]
    assert sample[1]["worker"] == evil["worker"]
    assert sample[2] == 3.0


def test_prometheus_interleaved_help_type_comments():
    """HELP/TYPE comments interleaved BETWEEN samples (and plain comments
    anywhere) parse: real scrape targets emit families in any order."""
    text = "\n".join(
        [
            "# HELP m_a first metric",
            "# TYPE m_a counter",
            'm_a{x="1"} 1',
            "# a stray comment",
            "# HELP m_b second metric",
            "# TYPE m_b gauge",
            "m_b 2.5",
            '# HELP m_a first metric',  # re-stated mid-stream
            'm_a{x="2"} 3',
            "",
        ]
    )
    parsed = parse_prometheus_text(text)
    assert parsed["help"] == {"m_a": "first metric", "m_b": "second metric"}
    assert parsed["type"] == {"m_a": "counter", "m_b": "gauge"}
    assert parsed["samples"] == [
        ("m_a", {"x": "1"}, 1.0),
        ("m_b", {}, 2.5),
        ("m_a", {"x": "2"}, 3.0),
    ]


def test_prometheus_empty_label_set_roundtrip():
    """Gateway-level counters render with NO label braces; the parser
    must return them with an empty labels dict, and `{}` explicitly in
    the text must parse the same way."""
    text = render_prometheus([], gateway_counters={"gateway_events": 9})
    parsed = parse_prometheus_text(text)
    assert ("distilp_gateway_events", {}, 9.0) in parsed["samples"]
    assert parse_prometheus_text("m_c{} 4\n")["samples"] == [("m_c", {}, 4.0)]
    with pytest.raises(ValueError):
        parse_prometheus_text("not a sample line at all{{{\n")


# -- flight recorder: exception classes on failure counters -----------------


def test_flight_records_solve_attempt_exception_class(fleet, model):
    """A tick whose solve attempt raises leaves the exception CLASS in its
    flight record next to the counter delta (the satellite contract: a
    bare counter is invisible post-mortem)."""
    fr = FlightRecorder(capacity=16)
    boom = {"n": 0}

    def hook(attempt):
        boom["n"] += 1
        if boom["n"] == 2:  # first tick publishes; second tick's solve dies
            raise ValueError("injected")

    sched = make_scheduler(
        fleet, model, flight=fr, flight_key="f", fault_hook=hook,
        breaker_threshold=0,
    )
    trace = generate_trace("mixed", 2, seed=3, base_fleet=fleet)
    sched.handle(trace[0])
    sched.handle(trace[1])  # solve fails; last-known-good is served
    recs = fr.snapshot("f")
    assert len(recs) == 2
    assert "exc" not in recs[0]
    assert recs[1]["exc"] == {"solve_attempt_failed": "ValueError"}
    assert recs[1]["counters_delta"].get("solve_attempt_failed") == 1


def test_flight_records_spec_presolve_exception_class(
    fleet, model, monkeypatch
):
    import distilp_tpu.sched.scheduler as sched_mod

    def explode(*a, **kw):
        raise ValueError("row-scale crossing")

    monkeypatch.setattr(sched_mod, "presolve_candidates", explode)
    fr = FlightRecorder(capacity=16)
    sched = make_scheduler(
        fleet, model, flight=fr, flight_key="f", speculative=True
    )
    # Deterministic presolve trigger: the forecaster always proposes one
    # candidate future whose drift puts it in a DIFFERENT digest bucket
    # than the just-banked fresh solve, so every solved tick reaches the
    # presolve dispatch — which the stub fails.
    def always_one(fleet_state, k):
        devs = [d.model_copy(deep=True) for d in fleet_state.device_list()]
        for d in devs:
            d.t_comm = d.t_comm * 3.0 + 1e-3
        return [(devs, 0.5)]

    sched.forecaster.forecast = always_one
    trace = generate_trace("mixed", 2, seed=3, base_fleet=fleet)
    for ev in trace:
        sched.handle(ev)
    recs = fr.snapshot("f")
    failed = [r for r in recs if r.get("exc")]
    assert failed, "no flight record carried the presolve exception class"
    assert failed[0]["exc"]["spec_presolve_failed"] == "ValueError"
    assert sched.metrics.counters["spec_presolve_failed"] >= 1


# -- solver diagnostics digest on the span / flight path --------------------


def test_scheduler_diagnostics_digest_on_span_and_flight(fleet, model):
    """Scheduler(diagnostics=True): the conv_* digest attaches to the
    sched.solve span and the flight record, while counters and placements
    stay identical to the undiagnosed run (telemetry, not behavior)."""
    trace = generate_trace("mixed", 4, seed=9, base_fleet=fleet)
    plain = make_scheduler(fleet, model)
    r1 = replay(plain, trace)

    fr = FlightRecorder(capacity=16)
    tracer = Tracer()
    diag = make_scheduler(
        fleet, model, diagnostics=True, tracer=tracer, flight=fr,
        flight_key="f",
    )
    r2 = replay(diag, trace)
    assert plain.metrics.counters == diag.metrics.counters
    assert [
        (v.result.k, tuple(v.result.w), v.result.obj_value) for v in r1.views
    ] == [
        (v.result.k, tuple(v.result.w), v.result.obj_value) for v in r2.views
    ]
    solve_spans = [s for s in tracer.spans() if s["name"] == "sched.solve"]
    assert solve_spans
    for s in solve_spans:
        attrs = s["attrs"]
        assert attrs["conv_rounds"] >= 1
        assert attrs["conv_lp_iters"] == attrs["ipm_iters_executed"]
        assert "conv_certified" in attrs
    recs = fr.snapshot("f")
    assert len(recs) == len(trace)
    for rec in recs:
        assert rec["convergence"]["conv_rounds"] >= 1

"""Analytic model-profiler conformance tests.

Golden values are the reference's own pinned regression numbers
(/root/reference/test/test_models.py:54-121), reproduced here from local
config fixtures (tests/configs/) instead of HF Hub downloads — no network.
"""

from pathlib import Path

import pytest

from distilp_tpu.common import ModelProfileSplit
from distilp_tpu.profiler import (
    load_config,
    parse_quantization_info,
    profile_model,
    profile_model_split,
)

CONFIGS = Path(__file__).resolve().parent / "configs"

BATCHES = [1, 2, 4]
SEQ_LEN = 128

ALL_CONFIGS = sorted(p.name for p in CONFIGS.glob("*.json"))


def _split(name: str) -> ModelProfileSplit:
    return profile_model_split(
        load_config(CONFIGS / name), B=BATCHES[0], L=SEQ_LEN, bs_list=BATCHES
    )


@pytest.mark.parametrize("name", ALL_CONFIGS)
def test_profile_all_models_sanity(name):
    # Mirrors reference test/test_models.py:29-51.
    data = _split(name)
    assert data.L > 0
    assert data.V > 0
    assert data.e_embed > 0
    assert data.ek > 0
    assert data.ev > 0
    assert data.b[1] > 0
    assert data.b_i[1] > 0
    assert data.f_q["decode"]["b_1"][1] > 0
    assert data.quantization in ["Q4_K", "Q5_K", "Q6_K", "Q8_0", "F16", "BF16", "F32"]
    assert len(data.b) == data.L + 1
    assert data.b[0] == 0  # synthetic index-0 row


def test_profile_qwen3_32b_6bit_golden():
    # Reference test/test_models.py:54-65.
    data = _split("qwen3_32b_6bit.json")
    assert data.L == 64
    assert data.V == 151936
    assert data.e_embed == 5120
    assert data.ek == 128
    assert data.ev == 128
    assert data.b[3] == 346214400.0
    assert data.b_i[3] == 1310720.0
    assert data.f_q["decode"]["b_1"][3] == 907018240.0
    assert data.quantization == "Q6_K"


def test_profile_llama_70b_4bit_golden():
    # Reference test/test_models.py:68-79.
    data = _split("llama3_70b_4bit.json")
    assert data.L == 80
    assert data.V == 128256
    assert data.e_embed == 8192
    assert data.ek == 128
    assert data.ev == 128
    assert data.b[3] == 454557696.0
    assert data.b_i[3] == 2097152.0
    assert data.f_q["decode"]["b_1"][3] == 1715470336.0
    assert data.quantization == "Q4_K"


def test_profile_qwen3_32b_bf16_golden():
    # Reference test/test_models.py:96-107.
    data = _split("qwen3_32b_bf16.json")
    assert data.b[3] == 904396800
    assert data.b_i[3] == 1310720
    assert data.f_q["decode"]["b_1"][3] == 907018240.0
    assert data.quantization == "BF16"


def test_profile_qwen3_14b_8bit_golden():
    # Reference test/test_models.py:110-121.
    data = _split("qwen3_14b_8bit.json")
    assert data.L == 40
    assert data.b[3] == 335462400.0
    assert data.b_i[3] == 1310720.0
    assert data.f_q["decode"]["b_1"][3] == 663224320.0
    assert data.quantization == "Q8_0"


def test_phase_flops_relationship():
    # prefill >= decode per layer; merged = prefill + decode tokens.
    cfg = load_config(CONFIGS / "llama31_8b_4bit.json")
    split = profile_model_split(cfg, B=1, L=SEQ_LEN, bs_list=[1])
    pre = split.f_q["prefill"]["b_1"][1]
    dec = split.f_q["decode"]["b_1"][1]
    assert pre > dec > 0


def test_batch_scaling_decode():
    # Decode FLOPs scale ~linearly with batch (token count is B).
    data = _split("llama31_8b_4bit.json")
    f1 = data.f_q["decode"]["b_1"][1]
    f4 = data.f_q["decode"]["b_4"][1]
    # attention core scales with B too; projections dominate => ~4x
    assert 3.5 < f4 / f1 < 4.5


def test_moe_component_metrics_qwen3_30b():
    data = _split("qwen3_30b_a3b_8bit.json")
    assert data.is_moe
    assert data.n_routed_experts == 128
    assert data.experts_per_token == 8
    assert data.moe_intermediate_size == 768
    assert data.total_moe_layers == 48
    assert data.moe_layer_indices == list(range(1, 49))
    assert len(data.attn_bytes) == 48
    for idx in data.moe_layer_indices:
        assert data.bytes_per_expert[idx] > 0
        assert data.flops_per_expert[idx] > 0
        assert data.router_bytes[idx] > 0
        assert data.router_flops[idx] > 0
        assert data.flops_per_active_expert_per_token[idx] > 0
    # Routed expert bytes: E * 3 projections dominate layer weight bytes.
    assert data.bytes_per_expert[1] * 128 < data.b[1]


def test_moe_deepseek_v3_structure():
    data = _split("deepseek_v3.json")
    assert data.is_moe
    assert data.n_routed_experts == 256
    assert data.n_shared_experts == 1
    assert data.first_k_dense_replace == 3
    # Dense-replaced layers carry no shared-expert cost; later layers do.
    assert data.bytes_shared_experts[1] == 0
    assert data.bytes_shared_experts[4] > 0
    assert data.flops_shared_experts[4] > 0
    # MLA attention bytes are far below a GQA-equivalent H*H*4 layout.
    assert 0 < data.attn_bytes[0] < 7168 * 7168 * 4


def test_moe_router_bytes_not_in_layer_bytes():
    # Reference parity: router weights are tracked separately and not added
    # to b (reference profiler/model.py:176-192).
    data = _split("qwen3_30b_a3b_8bit.json")
    cfg = load_config(CONFIGS / "qwen3_30b_a3b_8bit.json")
    expert_total = data.bytes_per_expert[1] * data.n_routed_experts
    assert data.b[1] == data.attn_bytes[0] + expert_total


def test_gpt_oss_mxfp4_quant_parsing():
    cfg = load_config(CONFIGS / "gpt_oss_20b_mxfp4.json")
    q = parse_quantization_info(cfg)
    assert q.bits == 4
    assert q.group_size == 128
    assert q.label == "Q4_K"
    assert "model.layers.*.self_attn" in q.exclude_patterns
    data = _split("gpt_oss_20b_mxfp4.json")
    # Attention is excluded from quantization -> stored at fp16.
    H = 2880
    head_size = H // 64
    kv_out = 8 * head_size
    expected_attn = (H * H * 2) + (H * kv_out * 2) + (H * kv_out * 2) + (H * H * 2)
    assert data.attn_bytes[0] == expected_attn


def test_split_roundtrip_and_scalar_extraction(tmp_path):
    data = _split("qwen3_32b_6bit.json")
    path = tmp_path / "model_profile.json"
    path.write_text(data.model_dump_json())
    loaded = ModelProfileSplit.model_validate_json(path.read_text())
    assert loaded == data
    scalar = loaded.to_model_profile("decode")
    assert scalar.b_layer == data.b[1]
    assert scalar.f_q["b_1"] == data.f_q["decode"]["b_1"][1]
    assert scalar.L == data.L


def test_profile_model_api_accepts_dict_and_path():
    import json

    raw = json.loads((CONFIGS / "llama31_8b_4bit.json").read_text())
    from_dict = profile_model(raw, batch_sizes=[1], sequence_length=64)
    from_path = profile_model(CONFIGS / "llama31_8b_4bit.json", batch_sizes=[1], sequence_length=64)
    assert from_dict == from_path


def test_unknown_model_type_rejected():
    with pytest.raises(ValueError, match="model_type"):
        load_config({"hidden_size": 8})
    with pytest.raises(ValueError, match="Unsupported"):
        load_config({"model_type": "not_a_real_arch"})

"""CLI tests: profiler and solver console entry points."""

import json
import os
from pathlib import Path

import pytest

from conftest import SHARD_MAP_SKIP_REASON, jax_shard_map_available

CONFIGS = Path(__file__).resolve().parent / "configs"
PROFILES = Path(__file__).resolve().parent / "profiles"

# The device-profiling CLI runs profile_device, whose t_comm measurement is
# the shard_map interconnect collectives; see SHARD_MAP_SKIP_REASON.
requires_shard_map = pytest.mark.skipif(
    not jax_shard_map_available(), reason=SHARD_MAP_SKIP_REASON
)


def test_profiler_cli_model(tmp_path, capsys):
    from distilp_tpu.cli.profiler_cli import main

    out = tmp_path / "mp.json"
    rc = main(
        [
            "model",
            "-r",
            str(CONFIGS / "llama31_8b_4bit.json"),
            "-o",
            str(out),
            "-s",
            "128",
            "--batches",
            "1,2",
        ]
    )
    assert rc == 0
    data = json.loads(out.read_text())
    assert data["L"] == 32
    assert "b_2" in data["f_q"]["decode"]


@requires_shard_map
def test_profiler_cli_device(tmp_path):
    from distilp_tpu.cli.profiler_cli import main

    knobs = {
        "DPERF_GEMM_WARMUP": "0",
        "DPERF_GEMM_ITERS": "1",
        "DPERF_MEM_MB": "4",
        "DPERF_DISK_FILE_MB": "2",
        "DPERF_DISK_CHUNK_MB": "1",
    }
    old = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    try:
        out = tmp_path / "dev.json"
        rc = main(
            [
                "device",
                "-r",
                str(CONFIGS / "llama31_8b_4bit.json"),
                "-o",
                str(out),
                "--max-batch-exp",
                "1",
            ]
        )
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert rc == 0
    data = json.loads(out.read_text())
    assert data["scpu"]["F32"]["b_1"] > 0
    assert data["is_head"]


def test_solver_cli_golden_fixture(tmp_path, capsys):
    from distilp_tpu.cli.solver_cli import main

    sol = tmp_path / "solution.json"
    rc = main(
        [
            "--profile",
            str(PROFILES / "hermes_70b"),
            "--backend",
            "cpu",
            "--kv-bits",
            "4bit",
            "--mip-gap",
            "1e-4",
            "--save-solution",
            str(sol),
        ]
    )
    assert rc == 0
    payload = json.loads(sol.read_text())
    assert payload["k"] == 40
    assert payload["obj_value"] == pytest.approx(29.643569, abs=1e-3)
    assert sum(payload["w"]) * payload["k"] == 80


def test_solver_cli_k_candidates_forwarded(tmp_path):
    # The reference parses --k-candidates but drops it (cli/solver.py:211);
    # here it must constrain the sweep.
    from distilp_tpu.cli.solver_cli import main
    from distilp_tpu.common import load_from_profile_folder

    sol = tmp_path / "solution.json"
    rc = main(
        [
            "--profile",
            str(PROFILES / "hermes_70b"),
            "--k-candidates",
            "8,10",
            "--kv-bits",
            "4bit",
            "--save-solution",
            str(sol),
        ]
    )
    assert rc == 0
    payload = json.loads(sol.read_text())
    assert payload["k"] in (8, 10)


def test_solver_cli_rejects_bad_folder(tmp_path):
    from distilp_tpu.cli.solver_cli import main

    assert main(["--profile", str(tmp_path / "nope")]) == 2


def test_solver_cli_moe_fixture(tmp_path):
    # End-to-end MoE co-assignment through the CLI on the Mixtral golden
    # folder: the solution JSON must carry the expert placement y.
    from distilp_tpu.cli.solver_cli import main

    sol = tmp_path / "solution.json"
    rc = main(
        [
            "--profile",
            str(PROFILES / "mixtral_8x7b"),
            "--kv-bits",
            "8bit",
            "--mip-gap",
            "1e-3",
            "--save-solution",
            str(sol),
        ]
    )
    assert rc == 0
    payload = json.loads(sol.read_text())
    assert sum(payload["y"]) == 8
    assert sum(payload["w"]) * payload["k"] == 32


def test_solver_cli_moe_off(tmp_path):
    from distilp_tpu.cli.solver_cli import main

    sol = tmp_path / "solution.json"
    rc = main(
        [
            "--profile",
            str(PROFILES / "mixtral_8x7b"),
            "--kv-bits",
            "8bit",
            "--moe",
            "off",
            "--save-solution",
            str(sol),
        ]
    )
    assert rc == 0
    assert "y" not in json.loads(sol.read_text())


def test_solver_cli_search_knobs_round_trip(tmp_path, capsys):
    """The jax-backend search knobs must reach halda_solve from the shell
    (the certificate warning tells users to raise them), and the solution
    output must state the certificate."""
    from unittest.mock import patch

    from distilp_tpu.cli import solver_cli

    sol = tmp_path / "solution.json"
    seen = {}
    real = solver_cli.main.__globals__  # noqa: F841 (documentation only)

    import distilp_tpu.solver as solver_pkg

    orig = solver_pkg.halda_solve

    def spy(*args, **kwargs):
        seen.update(
            {k: kwargs.get(k) for k in ("max_rounds", "beam", "ipm_iters", "node_cap")}
        )
        return orig(*args, **kwargs)

    with patch.object(solver_pkg, "halda_solve", side_effect=spy):
        rc = solver_cli.main(
            [
                "--profile",
                str(PROFILES / "hermes_70b"),
                "--kv-bits",
                "4bit",
                "--max-rounds",
                "12",
                "--beam",
                "6",
                "--ipm-iters",
                "18",
                "--node-cap",
                "128",
                "--save-solution",
                str(sol),
            ]
        )
    assert rc == 0
    assert seen == {"max_rounds": 12, "beam": 6, "ipm_iters": 18, "node_cap": 128}
    payload = json.loads(sol.read_text())
    assert "certified" in payload and "gap" in payload
    out = capsys.readouterr().out
    assert "Optimality:" in out


def test_solver_cli_expert_loads(tmp_path, capsys):
    """--expert-loads drives the load-weighted routing loop from the shell:
    the output prints an expert->device mapping and the solution JSON
    carries the concrete expert ids and load shares."""
    from distilp_tpu.cli.solver_cli import main

    loads = tmp_path / "loads.json"
    loads.write_text(json.dumps([5.0, 3.0] + [1.0] * 6))
    sol = tmp_path / "solution.json"
    rc = main(
        [
            "--profile",
            str(PROFILES / "mixtral_8x7b"),
            "--kv-bits",
            "8bit",
            "--mip-gap",
            "1e-3",
            "--expert-loads",
            str(loads),
            "--save-solution",
            str(sol),
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "Expert routing (load-weighted):" in out
    payload = json.loads(sol.read_text())
    assert sum(payload["y"]) == 8
    hosted = sorted(e for ids in payload["expert_of_device"] for e in ids)
    assert hosted == list(range(8))
    assert sum(payload["expert_load_share"]) == pytest.approx(1.0)

    # Inline comma-separated values work too, and a bad value errors cleanly.
    rc2 = main(
        [
            "--profile",
            str(PROFILES / "mixtral_8x7b"),
            "--kv-bits",
            "8bit",
            "--mip-gap",
            "1e-3",
            "--expert-loads",
            "5,3,1,1,1,1,1,1",
        ]
    )
    assert rc2 == 0
    assert main(
        [
            "--profile",
            str(PROFILES / "mixtral_8x7b"),
            "--expert-loads",
            "not,numbers",
        ]
    ) == 2


def test_solver_cli_warm_from_round_trip(tmp_path, capsys):
    """--save-solution then --warm-from: the saved assignment (and, for MoE,
    the persisted duals) seed the re-solve; the answer matches."""
    pytest.importorskip("jax")
    from distilp_tpu.cli.solver_cli import main

    sol = tmp_path / "solution.json"
    rc = main(
        [
            "--profile",
            str(PROFILES / "mixtral_8x7b"),
            "--backend",
            "jax",
            "--kv-bits",
            "8bit",
            "--mip-gap",
            "1e-3",
            "--save-solution",
            str(sol),
        ]
    )
    assert rc == 0
    saved = json.loads(sol.read_text())
    assert "duals" in saved  # MoE solves persist their root multipliers

    rc2 = main(
        [
            "--profile",
            str(PROFILES / "mixtral_8x7b"),
            "--backend",
            "jax",
            "--kv-bits",
            "8bit",
            "--mip-gap",
            "1e-3",
            "--warm-from",
            str(sol),
            "--save-solution",
            str(tmp_path / "warm.json"),
        ]
    )
    assert rc2 == 0
    warm = json.loads((tmp_path / "warm.json").read_text())
    assert warm["certified"]
    assert warm["obj_value"] == pytest.approx(saved["obj_value"], rel=2e-3)

    # A broken warm file errors cleanly instead of tracebacking.
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert main(
        [
            "--profile",
            str(PROFILES / "mixtral_8x7b"),
            "--warm-from",
            str(bad),
        ]
    ) == 2


def test_solver_cli_warm_from_conflicts_and_bad_types(tmp_path):
    from distilp_tpu.cli.solver_cli import main

    # Valid JSON of the wrong shape errors cleanly (no traceback).
    arr = tmp_path / "arr.json"
    arr.write_text("[5, 3, 1]")
    assert main(
        ["--profile", str(PROFILES / "mixtral_8x7b"), "--backend", "jax",
         "--warm-from", str(arr)]
    ) == 2
    # --warm-from + --expert-loads is rejected (the load-aware loop manages
    # its own warm starts; the seed would be silently dropped otherwise).
    assert main(
        [
            "--profile",
            str(PROFILES / "mixtral_8x7b"),
            "--backend",
            "jax",
            "--warm-from",
            str(arr),
            "--expert-loads",
            "5,3,1,1,1,1,1,1",
        ]
    ) == 2
    # The cpu backend has no warm-start hook: silently cold-solving would
    # contradict the flag, so the combination is rejected.
    assert main(
        ["--profile", str(PROFILES / "mixtral_8x7b"), "--warm-from", str(arr)]
    ) == 2
    # --raw-out is device-profiling-only on the profiler CLI.
    from distilp_tpu.cli.profiler_cli import main as pmain

    assert pmain(
        ["model", "-r", str(CONFIGS / "llama31_8b_4bit.json"),
         "--raw-out", str(tmp_path / "nope.json")]
    ) == 2


@requires_shard_map
def test_profiler_cli_raw_out_carries_stats(tmp_path, monkeypatch):
    """--raw-out persists the raw DeviceInfo with measurement spreads and
    capacity provenance — the observability the DeviceProfile mapping drops."""
    from distilp_tpu.cli.profiler_cli import main
    from distilp_tpu.profiler import DeviceInfo

    for k, v in {
        "DPERF_GEMM_WARMUP": "0",
        "DPERF_GEMM_ITERS": "2",
        "DPERF_MEM_MB": "4",
        "DPERF_DISK_FILE_MB": "2",
        "DPERF_DISK_CHUNK_MB": "1",
    }.items():
        monkeypatch.setenv(k, v)
    raw = tmp_path / "raw.json"
    rc = main(
        [
            "device",
            "-r",
            str(CONFIGS / "llama31_8b_4bit.json"),
            "-o",
            str(tmp_path / "dev.json"),
            "--max-batch-exp",
            "1",
            "--raw-out",
            str(raw),
        ]
    )
    assert rc == 0
    di = DeviceInfo.model_validate_json(raw.read_text())
    # Measurement spreads were recorded with valid ordering.
    assert di.stats, "raw DeviceInfo carries no measurement stats"
    st = next(iter(di.stats.values()))
    assert st.samples >= 1 and st.min <= st.p50 <= st.max


def test_solver_cli_per_k(tmp_path, capsys):
    """--per-k prints a certified entry for every feasible k and saves the
    winner; invalid combinations are rejected before any solve."""
    from distilp_tpu.cli.solver_cli import main

    sol = tmp_path / "sol.json"
    rc = main(
        [
            "--profile",
            str(PROFILES / "hermes_70b"),
            "--backend",
            "jax",
            "--mip-gap",
            "1e-4",
            "--per-k",
            "--save-solution",
            str(sol),
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    # Parse the per-k table rows (k / objective / certified / assignment)
    # rather than substring-counting "True" across the whole capture, which
    # any future status line could inflate.
    rows = [
        ln.split()
        for ln in out.splitlines()
        if ln.strip() and ln.split()[0].isdigit()
    ]
    assert len(rows) == 9  # all 9 feasible k's reported
    assert all(r[2] == "True" for r in rows)  # ...each one certified
    assert "Best: k=40" in out
    saved = json.loads(sol.read_text())
    assert saved["k"] == 40 and saved["certified"] is True

    # --per-k on the CPU backend (VERDICT r5 item 7): one HiGHS solve per
    # k, restricted to two candidates to keep the oracle loop fast.
    rc = main(
        [
            "--profile",
            str(PROFILES / "hermes_70b"),
            "--backend",
            "cpu",
            "--per-k",
            "--k-candidates",
            "20,40",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    rows = [
        line.split()
        for line in out.splitlines()
        if line.strip() and line.split()[0] in ("20", "40")
    ]
    assert len(rows) == 2
    assert all(r[2] == "True" for r in rows)  # HiGHS optima are exact
    assert "Best: k=40" in out


def test_solver_cli_serve_trace(tmp_path, capsys):
    """`solver serve` replays the bundled churn trace through the scheduler
    daemon: rc 0 with --fail-uncertified, a JSON summary line, and a
    metrics snapshot on disk — the same invocation `make smoke-sched` runs."""
    from distilp_tpu.cli.solver_cli import main

    metrics_out = tmp_path / "metrics.json"
    rc = main(
        [
            "serve",
            "--trace",
            str(Path(__file__).resolve().parent / "traces" / "scheduler_smoke_20.jsonl"),
            "--profile",
            str(PROFILES / "llama_3_70b" / "online"),
            "--synthetic-fleet",
            "4",
            "--fleet-seed",
            "11",
            "--k-candidates",
            "8,10",
            "--quiet",
            "--fail-uncertified",
            "--metrics-out",
            str(metrics_out),
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    summary = json.loads(out[-1])
    assert summary["replay"]["events"] == 20
    assert summary["replay"]["structural_uncertified"] == 0
    assert summary["replay"]["failed_ticks"] == 0
    assert summary["drift_warm_share"] >= 0.6
    saved = json.loads(metrics_out.read_text())
    assert saved["metrics"]["counters"]["events_total"] == 20
    assert saved["metrics"]["counters"].get("tick_uncertified", 0) == 0


def test_solver_cli_serve_rejects_bad_inputs(tmp_path):
    from distilp_tpu.cli.solver_cli import main

    # Missing trace file.
    rc = main(
        [
            "serve",
            "--trace",
            str(tmp_path / "nope.jsonl"),
            "--profile",
            str(PROFILES / "llama_3_70b" / "online"),
        ]
    )
    assert rc == 2

    # Malformed trace line.
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"kind": "leave"}\n')  # missing required 'name'
    rc = main(
        [
            "serve",
            "--trace",
            str(bad),
            "--profile",
            str(PROFILES / "llama_3_70b" / "online"),
        ]
    )
    assert rc == 2


def test_solver_cli_evaluate_renders_twin_report(tmp_path, capsys):
    """`solver evaluate` solves a golden fixture and renders both twin
    reports; --json output must validate against the report schemas and be
    deterministic under --check-determinism (the `make smoke-twin` gate)."""
    from distilp_tpu.cli.solver_cli import main
    from distilp_tpu.twin import RobustnessReport, TwinEvaluation

    rc = main(
        [
            "evaluate",
            "--profile",
            str(PROFILES / "llama_3_70b" / "online"),
            "--backend",
            "cpu",
            "--samples",
            "64",
            "--seed",
            "7",
            "--dropout-p",
            "0.05",
            "--check-determinism",
            "--json",
        ]
    )
    assert rc == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    ev = TwinEvaluation.model_validate(payload["evaluation"])
    rep = RobustnessReport.model_validate(payload["robustness"])
    # The twin executed the solver's optimum: the cross-check must agree.
    assert ev.rel_err is not None and ev.rel_err < 1e-9
    assert rep.samples == 64 and rep.seed == 7
    assert rep.p50_s <= rep.p95_s <= rep.p99_s
    assert len(rep.sensitivity) == 2


def test_solver_cli_evaluate_saved_solution_and_bad_inputs(tmp_path, capsys):
    from distilp_tpu.cli.solver_cli import main

    # Solve once, save, then evaluate the saved placement.
    sol = tmp_path / "sol.json"
    rc = main(
        [
            "--profile",
            str(PROFILES / "llama_3_70b" / "online"),
            "--backend",
            "cpu",
            "--kv-bits",
            "4bit",
            "--save-solution",
            str(sol),
        ]
    )
    assert rc == 0
    capsys.readouterr()
    rc = main(
        [
            "evaluate",
            "--profile",
            str(PROFILES / "llama_3_70b" / "online"),
            "--solution",
            str(sol),
            "--samples",
            "32",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "Digital-twin execution" in out
    assert "Robustness report" in out

    # Bad inputs: missing folder, unreadable solution.
    assert main(["evaluate", "--profile", str(tmp_path / "nope")]) == 2
    assert (
        main(
            [
                "evaluate",
                "--profile",
                str(PROFILES / "llama_3_70b" / "online"),
                "--solution",
                str(tmp_path / "missing.json"),
            ]
        )
        == 2
    )
    # Structurally invalid solution (window sums don't divide L): the
    # applicability gate must reject it instead of mispricing it.
    bad = tmp_path / "bad.json"
    bad.write_text(
        json.dumps({"k": 2, "w": [13, 26], "n": [13, 26],
                    "obj_value": 1.0, "sets": {"M1": [], "M2": [0, 1], "M3": []}})
    )
    rc = main(
        [
            "evaluate",
            "--profile",
            str(PROFILES / "llama_3_70b" / "online"),
            "--solution",
            str(bad),
        ]
    )
    assert rc == 2


def test_solver_cli_serve_risk_aware_flag(tmp_path, capsys):
    """`serve --risk-aware` publishes risk metrics and demonstrably changes
    warm-pool selection on the bundled churn trace (tick 1 serves the
    shallower k=8 runner-up instead of the k=10 objective winner)."""
    from distilp_tpu.cli.solver_cli import main

    trace = Path(__file__).resolve().parent / "traces" / "scheduler_smoke_20.jsonl"
    # One-event prefix keeps the test fast; the switch happens on tick 1.
    short = tmp_path / "short.jsonl"
    short.write_text(trace.read_text().strip().splitlines()[0] + "\n")
    rc = main(
        [
            "serve",
            "--trace",
            str(short),
            "--profile",
            str(PROFILES / "llama_3_70b" / "online"),
            "--synthetic-fleet",
            "4",
            "--fleet-seed",
            "11",
            "--k-candidates",
            "8,10",
            "--risk-aware",
            "--quiet",
        ]
    )
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["risk"]["evals"] == 1
    assert summary["risk"]["switches"] >= 1
    assert summary["risk"]["errors"] == 0
    assert summary["metrics"]["latency"]["twin_p95"]["count"] == 1

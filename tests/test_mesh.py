"""Multi-chip sharded-solve verification on the virtual 8-device CPU mesh.

The frontier is the framework's data-parallel axis: ``solve_sweep_sharded``
enters the same fused B&B program as the single-chip backend with the
``SearchState`` node arrays sharded across the mesh, so GSPMD partitions the
batched IPM and turns incumbent/compaction reductions into collectives.
These tests pin that the sharded path reaches the SAME certified answer as
the unsharded path — not just that it runs.
"""

from __future__ import annotations

import jax
import pytest

from distilp_tpu.common import load_model_profile, kv_bits_to_factor
from distilp_tpu.parallel import make_mesh, solve_sweep_sharded
from distilp_tpu.parallel.mesh import pad_cap_to_mesh
from distilp_tpu.solver.assemble import assemble
from distilp_tpu.solver.backend_jax import _best_bound, solve_sweep_jax
from distilp_tpu.solver.coeffs import assign_sets, build_coeffs, valid_factors_of_L
from distilp_tpu.utils import make_synthetic_fleet

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual CPU mesh"
)

MIP_GAP = 1e-3


def _instance(profiles_dir, M):
    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(M, seed=123)
    coeffs = build_coeffs(devs, model, kv_bits_to_factor("4bit"), assign_sets(devs))
    arrays = assemble(coeffs)
    kWs = [(k, model.L // k) for k in valid_factors_of_L(model.L) if model.L // k >= M]
    return arrays, coeffs, kWs


@pytest.mark.parametrize("M", [8, 16])
def test_sharded_matches_unsharded_to_certificate(profiles_dir, M):
    arrays, coeffs, kWs = _instance(profiles_dir, M)

    _, best = solve_sweep_jax(arrays, kWs, mip_gap=MIP_GAP, coeffs=coeffs)
    assert best is not None and best.certified

    mesh = make_mesh(8)
    state, sf = solve_sweep_sharded(arrays, kWs, coeffs, mesh, mip_gap=MIP_GAP)
    incumbent = float(state.incumbent)
    bound = float(_best_bound(state))

    # The sharded sweep must certify, not merely terminate.
    assert incumbent - bound <= MIP_GAP * abs(incumbent) + 1e-12
    # Same certificate window as the unsharded answer.
    assert incumbent == pytest.approx(best.obj_value, rel=2 * MIP_GAP)
    # And the incumbent assignment must be a real placement.
    W = dict(kWs)[int(sf.ks[int(state.inc_kidx)])]
    w = [int(round(x)) for x in state.inc_w]
    assert sum(w) == W
    assert all(wi >= 1 for wi in w)


def test_sharded_beam_is_mesh_aligned(profiles_dir):
    """The effective beam and cap are multiples of the mesh size, so every
    device solves the same number of frontier rows."""
    arrays, coeffs, kWs = _instance(profiles_dir, 16)
    mesh = make_mesh(8)
    # A deliberately awkward cap/beam request still certifies (the solver
    # rounds both up to mesh multiples internally).
    state, _ = solve_sweep_sharded(
        arrays, kWs, coeffs, mesh, mip_gap=MIP_GAP, beam=5, node_cap=20
    )
    incumbent = float(state.incumbent)
    bound = float(_best_bound(state))
    assert incumbent - bound <= MIP_GAP * abs(incumbent) + 1e-12
    assert state.node_lo.shape[0] % 8 == 0


def test_pad_cap_to_mesh():
    mesh = make_mesh(8)
    assert pad_cap_to_mesh(1, mesh) == 8
    assert pad_cap_to_mesh(8, mesh) == 8
    assert pad_cap_to_mesh(9, mesh) == 16


def test_sharded_moe_certifies(profiles_dir):
    """Wide-expert MoE over the mesh must earn the SAME root-bound
    certificate as the single-chip packed path (the Lagrangian decomposition
    seeding is shared, not single-chip-only)."""
    from distilp_tpu.profiler.api import profile_model
    from distilp_tpu.solver.moe import build_moe_arrays, adjust_model

    model = profile_model(
        "tests/configs/mixtral_8x7b.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()
    devs = make_synthetic_fleet(8, seed=7, pool_bytes=int(64e9))
    coeffs = build_coeffs(
        devs, adjust_model(model), kv_bits_to_factor("8bit"), assign_sets(devs)
    )
    arrays = assemble(coeffs, moe=build_moe_arrays(devs, model))
    kWs = [
        (k, model.L // k) for k in valid_factors_of_L(model.L) if model.L // k >= 8
    ]

    _, best = solve_sweep_jax(arrays, kWs, mip_gap=MIP_GAP, coeffs=coeffs)
    assert best is not None and best.certified

    mesh = make_mesh(8)
    state, sf = solve_sweep_sharded(arrays, kWs, coeffs, mesh, mip_gap=MIP_GAP)
    incumbent = float(state.incumbent)
    bound = float(_best_bound(state))
    assert incumbent - bound <= MIP_GAP * abs(incumbent) + 1e-12
    assert incumbent == pytest.approx(best.obj_value, rel=2 * MIP_GAP)
    y = [int(round(x)) for x in state.inc_y]
    assert sum(y) == model.n_routed_experts


def test_sharded_frontier_hlo_is_partitioned(profiles_dir):
    """Assert — in the compiled HLO, not the narrative — that the frontier
    arrays stay partitioned along the node axis: the output shardings of the
    compiled sharded program must split node_bound/node_lo/node_hi across
    the 8 mesh devices, and replicate the incumbent scalars. A future change
    that silently replicates the frontier fails here."""
    import jax.numpy as jnp

    from distilp_tpu.parallel.mesh import shard_state, state_shardings
    from distilp_tpu.solver.backend_jax import (
        BDTYPE,
        _init_state,
        _solve_fused,
        _sweep_data,
        build_standard_form,
        default_search_params,
        rounding_data,
    )

    arrays, coeffs, kWs = _instance(profiles_dir, 16)
    feasible = [(k, W) for (k, W) in kWs]
    sf = build_standard_form(arrays, coeffs, feasible)
    _, d_beam, d_iters = default_search_params(sf.moe, len(sf.ks))
    mesh = make_mesh(8)
    cap = pad_cap_to_mesh(256, mesh)
    beam = pad_cap_to_mesh(d_beam, mesh)

    data = _sweep_data(sf, rounding_data(coeffs, arrays.moe))
    state = shard_state(_init_state(sf, cap=cap), mesh)
    gap = jnp.asarray(MIP_GAP, BDTYPE)

    with mesh:
        lowered = _solve_fused.lower(
            data, state, gap, ipm_iters=d_iters, max_rounds=8,
            beam=beam, moe=sf.moe,
        )
        compiled = lowered.compile()

    out_shardings = compiled.output_shardings
    fields = type(state)._fields
    by_name = dict(zip(fields, jax.tree.leaves(out_shardings)))

    n_mesh = 8
    for name in ("node_bound", "node_lo", "node_hi", "node_kidx", "active"):
        sh = by_name[name]
        shape = getattr(state, name).shape
        # Partitioned: each device holds 1/8 of the node axis.
        assert sh.shard_shape(shape)[0] == shape[0] // n_mesh, (
            f"{name} is not partitioned along the node axis: "
            f"{sh.shard_shape(shape)} vs global {shape}"
        )
    for name in ("incumbent", "inc_kidx"):
        sh = by_name[name]
        shape = getattr(state, name).shape
        assert sh.shard_shape(shape) == shape, f"{name} should be replicated"


def test_sharded_per_k_certifies_every_k(profiles_dir):
    """The per-k pruning regime must work under GSPMD too: a sharded sweep
    with per_k=True closes every feasible k's own certificate, matching the
    single-chip per-k solve."""
    import jax.numpy as jnp
    import numpy as np

    from distilp_tpu.common import kv_bits_to_factor, load_from_profile_folder
    from distilp_tpu.parallel import make_mesh, solve_sweep_sharded
    from distilp_tpu.solver.api import halda_solve_per_k
    from distilp_tpu.solver.assemble import assemble
    from distilp_tpu.solver.backend_jax import _per_k_bound
    from distilp_tpu.solver.coeffs import (
        assign_sets,
        build_coeffs,
        valid_factors_of_L,
    )

    devs, model = load_from_profile_folder(profiles_dir / "hermes_70b")
    coeffs = build_coeffs(
        devs, model, kv_bits_to_factor("4bit"), assign_sets(devs)
    )
    arrays = assemble(coeffs)
    kWs = [(k, model.L // k) for k in valid_factors_of_L(model.L)]
    gap = 1e-4

    mesh = make_mesh(8)
    state, sf = solve_sweep_sharded(
        arrays, kWs, coeffs, mesh, mip_gap=gap, per_k=True
    )
    inc_k = np.asarray(state.per_k_best)
    bound_k = np.asarray(_per_k_bound(state))
    w_k = np.asarray(state.per_k_w)

    solo = {r.k: r for r in halda_solve_per_k(devs, model, mip_gap=gap,
                                              kv_bits="4bit")}
    assert len(solo) == len(sf.ks)
    for j, k in enumerate(sf.ks):
        assert np.isfinite(inc_k[j]), f"k={k} found no incumbent sharded"
        certified = (
            np.isposinf(bound_k[j])
            or inc_k[j] - bound_k[j] <= gap * abs(inc_k[j]) + 1e-12
        )
        assert certified, f"k={k} missed its certificate on the mesh"
        tol = 2 * gap * abs(solo[k].obj_value) + 1e-9
        assert abs(inc_k[j] - solo[k].obj_value) <= tol
        assert int(sum(w_k[j])) * k == model.L
    assert jnp.isfinite(state.incumbent)

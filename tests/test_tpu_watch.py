"""tools/tpu_watch.py: structured probe attempts + the --json surface.

ROADMAP item 6's watcher had zero test coverage; these pin the parts a
wedged-tunnel post-mortem depends on: the probe child's phase trail
(WHERE init died), the compile-ledger counters riding the first-dispatch
phase, and the machine-readable --json output including the bench-shaped
``tpu_error`` block. All probe subprocesses are monkeypatched — no test
here may touch a backend (that wedging is the whole point).
"""

from __future__ import annotations

import json

import bench
import tools.tpu_watch as tw

_LIVE_STDOUT = (
    "DPERF_PHASE interp\n"
    "DPERF_PHASE jax_import\n"
    "DPERF_PHASE backend_init\n"
    'DPERF_PHASE first_dispatch {"compiles": 1, "unattributed_compiles": 1}\n'
    "DPERF_PROBE tpu 4\n"
)


def test_parse_probe_phases_trail_and_ledger():
    phases = bench.parse_probe_phases(_LIVE_STDOUT)
    assert [p["phase"] for p in phases] == [
        "interp", "jax_import", "backend_init", "first_dispatch"
    ]
    assert phases[-1]["ledger"]["compiles"] == 1
    # Library chatter and the platform sentinel never parse as phases.
    assert bench.parse_probe_phases("hello\nDPERF_PROBE cpu 1\n") == []


def test_probe_attempt_live(monkeypatch):
    monkeypatch.setattr(
        bench, "_run_probe_once", lambda t: (0, _LIVE_STDOUT, "")
    )
    platform, rec = tw.probe_attempt(5.0, attempt=3)
    assert platform == "tpu"
    assert rec["outcome"] == "ok" and rec["platform"] == "tpu"
    assert rec["attempt"] == 3
    assert rec["phases"][-1] == "first_dispatch"
    assert rec["ledger"]["compiles"] == 1


def test_probe_attempt_timeout_records_wedge_point(monkeypatch):
    # A killed-at-timeout child left a partial trail: the wedge is at
    # backend init — the axon-tunnel class, not an environment problem.
    partial = "DPERF_PHASE interp\nDPERF_PHASE jax_import\n"
    monkeypatch.setattr(
        bench, "_run_probe_once", lambda t: (None, partial, "")
    )
    platform, rec = tw.probe_attempt(5.0)
    assert platform is None
    assert rec["outcome"] == "timeout"
    assert rec["wedged_after"] == "jax_import"
    # No output at all = never got past spawn.
    monkeypatch.setattr(bench, "_run_probe_once", lambda t: (None, "", ""))
    _, rec = tw.probe_attempt(5.0)
    assert rec["wedged_after"] == "spawn"


def _isolate_captures(monkeypatch, tmp_path):
    """Keep the watcher's restart-safe artifact commits OUT of tests: a
    checkout with a captured BENCH_tpu_capture.json must never have a
    unit test run `git commit` on it."""
    monkeypatch.setattr(tw, "_commit", lambda paths, msg: False)
    monkeypatch.setattr(tw, "BENCH_OUT", tmp_path / "BENCH_tpu_capture.json")
    monkeypatch.setattr(tw, "FIXDIR", tmp_path / "tpu_v5e")


def test_json_once_smoke_cpu_backend(monkeypatch, capsys, tmp_path):
    _isolate_captures(monkeypatch, tmp_path)
    cpu = "DPERF_PHASE interp\nDPERF_PROBE cpu 1\n"
    monkeypatch.setattr(bench, "_run_probe_once", lambda t: (0, cpu, ""))
    rc = tw.main(["--once", "--json", "--probe-timeout", "1"])
    out = capsys.readouterr()
    payload = json.loads(out.out)  # stdout is EXACTLY one JSON object
    assert rc == 2 and payload["exit"] == 2
    assert len(payload["attempts"]) == 1
    assert payload["attempts"][0]["outcome"] == "ok"
    assert payload["bench_captured"] is False
    # A cpu-only probe is not a live window: the bench-shaped error
    # block must say so, not be silently absent.
    assert "cpu fallback" in payload["tpu_error"]["error"]
    # Human log moved to stderr in --json mode.
    assert "probe #1" in out.err


_MEM_PROBE_STDOUT = (
    'DPERF_MEM {"devices": ['
    '{"id": 0, "platform": "tpu", "kind": "TPU v5e", "memory_stats": '
    '{"bytes_in_use": 1048576, "bytes_limit": 17179869184, '
    '"peak_bytes_in_use": 2097152}}, '
    '{"id": 1, "platform": "tpu", "kind": "TPU v5e", "memory_stats": '
    '{"bytes_in_use": 524288, "bytes_limit": 17179869184, '
    '"peak_bytes_in_use": 1048576}}]}\n'
)


def test_probe_device_memory_sums_hbm_stats(monkeypatch):
    monkeypatch.setattr(tw, "_run", lambda cmd, t, env=None: (0, _MEM_PROBE_STDOUT, ""))
    block = tw.probe_device_memory(5.0)
    assert block is not None
    assert len(block["devices"]) == 2
    assert block["hbm_limit_bytes_total"] == 2 * 17179869184
    assert block["hbm_in_use_bytes_total"] == 1048576 + 524288
    assert block["hbm_peak_bytes_total"] == 2097152 + 1048576


def test_probe_device_memory_absent_on_cpu_only(monkeypatch):
    # The CPU backend's memory_stats() is None -> the child emits devices
    # WITHOUT a memory_stats key -> the block is ABSENT, never zeroed.
    cpu_out = 'DPERF_MEM {"devices": [{"id": 0, "platform": "cpu", "kind": "cpu"}]}\n'
    monkeypatch.setattr(tw, "_run", lambda cmd, t, env=None: (0, cpu_out, ""))
    assert tw.probe_device_memory(5.0) is None
    # A wedged/failed probe child is also an absence, not a crash.
    monkeypatch.setattr(tw, "_run", lambda cmd, t, env=None: (None, "", ""))
    assert tw.probe_device_memory(5.0) is None
    monkeypatch.setattr(tw, "_run", lambda cmd, t, env=None: (0, "DPERF_MEM not-json\n", ""))
    assert tw.probe_device_memory(5.0) is None


def test_json_cpu_probe_has_no_memory_block(monkeypatch, capsys, tmp_path):
    _isolate_captures(monkeypatch, tmp_path)
    cpu = "DPERF_PHASE interp\nDPERF_PROBE cpu 1\n"
    monkeypatch.setattr(bench, "_run_probe_once", lambda t: (0, cpu, ""))
    tw.main(["--once", "--json", "--probe-timeout", "1"])
    payload = json.loads(capsys.readouterr().out)
    # A cpu-only probe never opened a TPU window: the memory block must
    # be absent (not zeroed) — same contract as the ledger's gauges.
    assert "memory" not in payload


def test_json_live_window_carries_memory_block(monkeypatch, capsys, tmp_path):
    _isolate_captures(monkeypatch, tmp_path)
    monkeypatch.setattr(
        bench, "_run_probe_once", lambda t: (0, _LIVE_STDOUT, "")
    )
    monkeypatch.setattr(tw, "_run", lambda cmd, t, env=None: (0, _MEM_PROBE_STDOUT, ""))
    # Captures are stubbed failures: the watcher must still report the
    # HBM stats it grabbed while the window was open.
    monkeypatch.setattr(tw, "_capture_bench", lambda t: False)
    monkeypatch.setattr(tw, "_capture_fixtures", lambda t: False)
    rc = tw.main(["--once", "--json", "--probe-timeout", "1"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 2
    assert payload["memory"]["hbm_limit_bytes_total"] == 2 * 17179869184
    assert len(payload["memory"]["devices"]) == 2
    assert "tpu_error" not in payload  # a live window is not an error


def test_json_wedged_emits_bench_shaped_tpu_error(monkeypatch, capsys, tmp_path):
    _isolate_captures(monkeypatch, tmp_path)
    partial = (
        "DPERF_PHASE interp\nDPERF_PHASE jax_import\n"
        "DPERF_PHASE backend_init\n"
    )
    monkeypatch.setattr(
        bench, "_run_probe_once", lambda t: (None, partial, "")
    )
    rc = tw.main(["--once", "--json", "--probe-timeout", "1"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 2
    err = payload["tpu_error"]
    # The bench block's vocabulary: error text naming the wedge point,
    # retries, and the full attempt trail.
    assert "backend_init" in err["error"]
    assert err["retries"] == 1
    assert err["attempts"][0]["wedged_after"] == "backend_init"

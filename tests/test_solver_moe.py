"""MoE expert+layer co-assignment solver tests.

The capability the reference advertises ("layer/expert assignment",
/root/reference/pyproject.toml:4) and profiles (profiler/model.py:1059-1073)
but never solves — there are no reference numbers to pin, so these tests
check formulation invariants and CPU/JAX backend agreement instead.
"""

from __future__ import annotations

import pytest

from distilp_tpu.profiler.api import profile_model
from distilp_tpu.solver import halda_solve
from distilp_tpu.solver.moe import (
    adjust_model,
    build_moe_arrays,
    model_has_moe_components,
)
from distilp_tpu.utils import make_synthetic_fleet

from pathlib import Path

CONFIGS = Path(__file__).resolve().parent / "configs"
MIXTRAL = str(CONFIGS / "mixtral_8x7b.json")


def moe_fleet(M: int, seed: int, ram: float = 64e9):
    """Synthetic fleet with enough memory to actually hold the expert set.

    Expert residency is hard-capped (experts are hit at every MoE layer and
    cannot disk-stream), so MoE instances need fleets whose pools can hold
    E expert slices — Mixtral 8x7B carries ~10 GB per expert slot."""
    return make_synthetic_fleet(M, seed=seed, pool_bytes=int(ram))


@pytest.fixture(scope="module")
def moe_model():
    split = profile_model(MIXTRAL, batch_sizes=[1], sequence_length=128)
    return split.to_model_profile()


def test_moe_detection(moe_model):
    assert model_has_moe_components(moe_model)
    assert moe_model.n_routed_experts == 8
    assert moe_model.experts_per_token == 2
    assert moe_model.total_moe_layers == moe_model.L == 32


def test_adjust_model_strips_expert_cost(moe_model):
    adj = adjust_model(moe_model)
    # Every Mixtral layer is MoE: the adjusted typical layer is just
    # attention + router (+ zero shared experts) — far below the full layer.
    assert adj.b_layer < 0.1 * moe_model.b_layer
    assert adj.f_q["b_1"] < moe_model.f_q["b_1"]
    # Architecture and KV fields untouched.
    assert adj.L == moe_model.L and adj.n_kv == moe_model.n_kv


def test_build_moe_arrays(moe_model):
    devs = make_synthetic_fleet(4, seed=7)
    moe = build_moe_arrays(devs, moe_model)
    assert moe.E == 8 and moe.n_moe == 32
    assert moe.g_raw.shape == (4,) and (moe.g_raw > 0).all()
    # Resident bytes per expert-slot: all 32 layers' slice of one expert,
    # charged to exactly one pool per device.
    eb_total = moe.eb_ram + moe.eb_vram
    assert (eb_total > 32 * 3e8).all()
    assert ((moe.eb_ram == 0) | (moe.eb_vram == 0)).all()
    # The fleet cycles mac_metal/linux_cuda/linux_cpu/android: the CUDA box
    # (index 1) has the faster expert table, so its slice lives in VRAM;
    # the others charge their primary pool.
    assert moe.eb_vram[1] > 0 and moe.eb_ram[1] == 0
    assert moe.eb_vram[0] == moe.eb_vram[2] == moe.eb_vram[3] == 0


def test_cpu_moe_solve(moe_model):
    devs = moe_fleet(4, seed=7)
    res = halda_solve(devs, moe_model, kv_bits="8bit", backend="cpu", mip_gap=1e-3)
    assert res.y is not None
    assert sum(res.y) == moe_model.n_routed_experts
    assert all(0 <= yi <= moe_model.n_routed_experts for yi in res.y)
    assert sum(res.w) * res.k == moe_model.L


def test_moe_off_by_flag(moe_model):
    devs = moe_fleet(4, seed=7)
    res = halda_solve(
        devs, moe_model, kv_bits="8bit", backend="cpu", mip_gap=1e-3, moe=False
    )
    assert res.y is None


def test_moe_flag_requires_components():
    from distilp_tpu.common import load_from_profile_folder

    devs, model = load_from_profile_folder(
        CONFIGS.parent / "profiles" / "hermes_70b"
    )
    with pytest.raises(ValueError):
        halda_solve(devs, model, moe=True)


def test_memory_affinity(moe_model):
    """Experts should concentrate on the device with memory headroom."""
    devs = moe_fleet(2, seed=3)
    big, small = devs[0], devs[1]
    big.d_avail_ram = int(400e9)
    if big.d_avail_metal is not None:
        big.d_avail_metal = int(400e9)
    small.d_avail_ram = int(2e9)
    if small.d_avail_metal is not None:
        small.d_avail_metal = int(2e9)
    if small.d_avail_cuda is not None:
        small.d_avail_cuda = int(2e9)
    res = halda_solve(devs, moe_model, kv_bits="8bit", backend="cpu", mip_gap=1e-3)
    assert res.y is not None
    assert res.y[0] > res.y[1]


@pytest.mark.parametrize("M", [4, 8])
def test_jax_matches_cpu(moe_model, M):
    devs = moe_fleet(M, seed=7)
    gap = 1e-3
    ref = halda_solve(devs, moe_model, kv_bits="8bit", backend="cpu", mip_gap=gap)
    got = halda_solve(devs, moe_model, kv_bits="8bit", backend="jax", mip_gap=gap)
    assert got.y is not None and sum(got.y) == moe_model.n_routed_experts
    assert got.certified and got.gap is not None and got.gap <= gap
    # Both backends certify the same relative gap; their incumbents may
    # differ by at most twice that.
    tol = 2 * gap * abs(ref.obj_value) + 1e-9
    assert abs(got.obj_value - ref.obj_value) <= tol


def test_gpu_heavy_fleet_experts_shift_to_accelerators(moe_model):
    """On a fleet mixing fast-GPU boxes and CPU-only boxes with EQUAL memory,
    expert placement must favor the accelerator devices (their expert slices
    run on the GPU table and live in VRAM), and the CPU oracle must agree —
    the v1 formulation priced every expert at CPU speed and charged RAM, so
    a GPU-heavy fleet's expert objective was systematically wrong."""
    # 2 CUDA boxes + 2 slow CPU-only boxes, equal memory and t_comm: the
    # only expert signal left is compute throughput and the VRAM pool.
    pool = moe_fleet(8, seed=1)
    devs = [pool[1], pool[5], pool[2], pool[6]]  # cuda, cuda, cpu, cpu
    for i, d in enumerate(devs):
        d.is_head = i == 0
        d.t_comm = 0.01
        if d.d_avail_cuda is not None:
            d.d_avail_cuda = int(250e9)
        else:
            # Slow, GPU-less edge boxes: expert FLOPs on them actually hurt.
            d.scpu = {
                q: {b: v / 50.0 for b, v in cols.items()}
                for q, cols in d.scpu.items()
            }
    moe = build_moe_arrays(devs, moe_model)
    assert (moe.eb_vram[[0, 1]] > 0).all() and (moe.eb_vram[[2, 3]] == 0).all()
    # GPU expert throughput beats the slow CPUs: smaller busy coefficient.
    assert moe.g_raw[0] < moe.g_raw[2] and moe.g_raw[1] < moe.g_raw[3]

    gap = 1e-3
    ref = halda_solve(devs, moe_model, kv_bits="8bit", backend="cpu", mip_gap=gap)
    got = halda_solve(devs, moe_model, kv_bits="8bit", backend="jax", mip_gap=gap)
    tol = 2 * gap * abs(ref.obj_value) + 1e-9
    assert abs(got.obj_value - ref.obj_value) <= tol
    # Accelerator devices host the majority of the expert set.
    assert got.y[0] + got.y[1] > got.y[2] + got.y[3]


def test_expert_residency_is_hard_capped(moe_model):
    """A fleet whose pools cannot physically hold the E expert slices is
    reported infeasible — not 'optimal' at a disk penalty the hardware could
    never realize (expert weights are needed at every MoE layer and cannot
    ride the layer-streaming slack)."""
    devs = moe_fleet(2, seed=3, ram=4e9)  # ~10 GB per expert slot won't fit
    with pytest.raises(RuntimeError, match="No feasible"):
        halda_solve(devs, moe_model, kv_bits="8bit", backend="cpu", mip_gap=1e-3)
    with pytest.raises(RuntimeError, match="No feasible"):
        halda_solve(devs, moe_model, kv_bits="8bit", backend="jax", mip_gap=1e-3)


def test_deepseek_v3_flagship_certified():
    """The wide-expert flagship (DeepSeek-V3: E=256 routed experts over a
    32-device fleet) solves to a CERTIFIED mip_gap<=1e-3 with no
    RuntimeWarning, and its incumbent matches the HiGHS oracle. The LP root
    integrality gap here is structural (box branching alone stalls ~7%
    short); the Lagrangian decomposition root bounds close it
    (backend_jax._decomp_bound_roots)."""
    import warnings

    split = profile_model(
        str(CONFIGS / "deepseek_v3.json"), batch_sizes=[1], sequence_length=128
    )
    model = split.to_model_profile()
    assert model.n_routed_experts == 256
    # ~1.6 GB per expert slot x 256 slots: the fleet needs ~420 GB of pools
    # to hold the expert set honestly (residency is hard-capped).
    devs = moe_fleet(32, seed=11, ram=32e9)
    gap = 1e-3
    ref = halda_solve(devs, model, kv_bits="8bit", backend="cpu", mip_gap=gap)
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        got = halda_solve(devs, model, kv_bits="8bit", backend="jax", mip_gap=gap)
    assert got.certified and got.gap is not None and got.gap <= gap
    tol = 2 * gap * abs(ref.obj_value) + 1e-9
    assert abs(got.obj_value - ref.obj_value) <= tol
    assert sum(got.y) == 256 and sum(got.w) * got.k == model.L

"""Speculative replanning: forecaster, bank digest/tolerance, scheduler
probe/presolve semantics, snapshot round trips, and chaos interaction.

Solver-backed tests follow test_sched's recipe: the JAX backend on CPU
with a small L=32 model and a restricted k-grid, fleet shapes kept to a
handful so jit compiles amortize across the module.
"""

from __future__ import annotations

import json
import math

import pytest

from distilp_tpu.sched import (
    BankEntry,
    ChurnForecaster,
    DeviceDegrade,
    FaultPlan,
    FaultSpec,
    FleetState,
    LoadTick,
    Scheduler,
    SpeculationBank,
    chaos_replay,
    generate_trace,
    instance_digest,
    read_trace,
    replay,
)
from distilp_tpu.sched.metrics import HEALTH_HEALTHY, registry_help
from distilp_tpu.sched.sim import SCENARIOS
from distilp_tpu.solver.result import HALDAResult
from distilp_tpu.utils import make_synthetic_fleet

GAP = 1e-3
KS = [4, 8]


@pytest.fixture(scope="module")
def model():
    from distilp_tpu.profiler.api import profile_model

    return profile_model(
        "tests/configs/llama31_8b_4bit.json", batch_sizes=[1],
        sequence_length=128,
    ).to_model_profile()


@pytest.fixture()
def fleet():
    return make_synthetic_fleet(4, seed=11)


def make_scheduler(fleet, model, **kw):
    kw.setdefault("mip_gap", GAP)
    kw.setdefault("kv_bits", "4bit")
    kw.setdefault("backend", "jax")
    kw.setdefault("k_candidates", KS)
    return Scheduler(fleet, model, **kw)


def _result(k=4, obj=1.0):
    return HALDAResult(
        w=[8, 8, 8, 8], n=[1, 1, 1, 1], k=k, obj_value=obj, sets={}
    )


# -- forecaster (no solver) -------------------------------------------------


def test_forecaster_deterministic_and_revert(fleet, model):
    fs = FleetState(fleet, model)
    names = list(fs.devices)
    fc1, fc2 = ChurnForecaster(), ChurnForecaster()
    for scale in (1.3, 1.1, 0.9):
        fs.apply(DeviceDegrade(name=names[1], t_comm_scale=scale))
        fc1.observe(fs)
        fc2.observe(fs)
    # Same applied stream -> bit-identical state and forecasts.
    assert fc1.dump_state() == fc2.dump_state()
    c1 = fc1.forecast(fs, 3)
    c2 = fc2.forecast(fs, 3)
    assert len(c1) == len(c2) > 0
    for (d1, w1), (d2, w2) in zip(c1, c2):
        assert w1 == w2
        assert [d.t_comm for d in d1] == [d.t_comm for d in d2]
    # Candidate 0 is the revert: the perturbed channel back to its value
    # before the last change, everything else held.
    ch = fc1.channel(names[1])
    revert_devs, w0 = c1[0]
    by_name = {d.name: d for d in revert_devs}
    assert by_name[names[1]].t_comm == pytest.approx(ch["prev"])
    assert w0 == max(w for _, w in c1)
    # Weights normalize over the emitted list.
    assert sum(w for _, w in c1) == pytest.approx(1.0)


def test_forecaster_trend_tracks_decay(fleet, model):
    fs = FleetState(fleet, model)
    name = list(fs.devices)[2]
    fc = ChurnForecaster()
    fc.observe(fs)
    for _ in range(6):
        fs.apply(DeviceDegrade(name=name, t_comm_scale=1.05))
        fc.observe(fs)
    ch = fc.channel(name)
    # Six compounding +5% degrades: the smoothed log-trend converges on
    # log(1.05), so the trend candidate predicts continued decay.
    assert ch["trend"] == pytest.approx(math.log(1.05), rel=0.05)
    live = fs.devices[name].t_comm
    trends = [
        devs for devs, _w in fc.forecast(fs, 3)
        for d in devs if d.name == name and d.t_comm > live
    ]
    assert trends, "no candidate continues the decay trend"


def test_forecaster_drops_departed_and_skips_nonfinite(fleet, model):
    fs = FleetState(fleet, model)
    names = list(fs.devices)
    fc = ChurnForecaster()
    fc.observe(fs)
    assert len(fc) == len(names)
    from distilp_tpu.sched import DeviceLeave

    fs.apply(DeviceLeave(name=names[-1]))
    fc.observe(fs)
    assert len(fc) == len(names) - 1
    assert fc.channel(names[-1]) is None
    # Defensive finite gate: a NaN channel never enters the EWMA state
    # (the scheduler's quarantine keeps this from happening upstream).
    fs.devices[names[1]].t_comm = float("nan")
    fc.observe(fs)
    ch = fc.channel(names[1])
    assert all(math.isfinite(v) for v in ch.values())


# -- digest + bank (no solver) ---------------------------------------------


def test_instance_digest_tolerance_buckets(fleet, model):
    fs = FleetState(fleet, model)
    names = list(fs.devices)
    tol = 0.05
    base = instance_digest(fs, tol)
    assert base == instance_digest(fs, tol)  # deterministic
    # A large excursion moves the digest; its exact inverse restores it.
    fs.apply(DeviceDegrade(name=names[1], t_comm_scale=1.5))
    spiked = instance_digest(fs, tol)
    assert spiked != base
    fs.apply(DeviceDegrade(name=names[1], t_comm_scale=1 / 1.5))
    assert instance_digest(fs, tol) == base
    # Unforecast channels are digest-visible too (honest-miss contract):
    # bandwidth and memory drift change the digest.
    fs.devices[names[2]].comm_bandwidth = 1e9
    bw = instance_digest(fs, tol)
    fs.apply(DeviceDegrade(name=names[2], bandwidth_scale=0.5))
    assert instance_digest(fs, tol) != bw
    mem = instance_digest(fs, tol)
    fs.apply(DeviceDegrade(name=names[2], mem_scale=0.5))
    assert instance_digest(fs, tol) != mem


def test_bank_lru_probe_and_invalidate():
    bank = SpeculationBank(capacity=2, tolerance=0.05)
    key = ("f", "m")
    for i, digest in enumerate(("d0", "d1", "d2")):
        bank.put(
            digest,
            BankEntry(result=_result(obj=i), key=key, weight=1.0,
                      solved_seq=i),
        )
    assert len(bank) == 2 and "d0" not in bank  # LRU bound
    assert bank.probe("d1", key).result.obj_value == 1.0
    assert bank.probe("d1", ("other", "m")) is None  # identity gate
    bank.put(
        "d3", BankEntry(result=_result(), key=("g", "m"), weight=0.5,
                        solved_seq=9)
    )
    assert bank.invalidate(("g", "m")) == 1  # drops the stale ("f","m") one
    # capacity=2: d1 (renewed by the probe) and d3 were live; only d3
    # matches the surviving key.
    assert len(bank) == 1 and "d3" in bank


def test_bank_state_roundtrip_bit_exact():
    import numpy as np

    bank = SpeculationBank(capacity=4, tolerance=0.1)
    res = _result()
    res.ipm_state = {"v": np.arange(6, dtype=np.float32).reshape(2, 3)}
    bank.put(
        "dd", BankEntry(result=res, key=("f", "m"), weight=0.25,
                        solved_seq=3)
    )
    blob = json.loads(json.dumps(bank.dump_state()))  # wire trip
    other = SpeculationBank(capacity=4, tolerance=0.1)
    other.load_state(blob)
    got = other.probe("dd", ("f", "m"))
    assert got.weight == 0.25 and got.solved_seq == 3
    assert got.result.model_dump() == res.model_dump()
    assert np.array_equal(got.result.ipm_state["v"], res.ipm_state["v"])
    assert got.result.ipm_state["v"].dtype == np.float32
    other.load_state(None)  # old snapshots without the block restore clean
    assert len(other) == 0


# -- spec trace scenarios ---------------------------------------------------


def test_spec_scenarios_drift_only_and_deterministic(fleet):
    assert "spec_burst" in SCENARIOS and "spec_flap" in SCENARIOS
    for scenario in ("spec_burst", "spec_flap"):
        trace = generate_trace(scenario, 40, seed=9, base_fleet=fleet)
        again = generate_trace(scenario, 40, seed=9, base_fleet=fleet)
        assert [e.model_dump() for e in trace] == [
            e.model_dump() for e in again
        ]
        # t_comm-only drift: no structural churn, no bandwidth/mem decay,
        # no expert loads — the channels the forecaster models.
        assert {e.kind for e in trace} <= {"load", "degrade"}
        for e in trace:
            if e.kind == "degrade":
                assert e.bandwidth_scale == 1.0 and e.mem_scale == 1.0
            else:
                assert e.expert_loads is None and e.t_comm_jitter
        # Oscillation events alternate exactly: consecutive jitters on the
        # subset are element-wise inverses.
        osc = [e for e in trace if e.kind == "load"]
        assert len(osc) >= 2
        for a, b in zip(osc, osc[1:]):
            assert set(a.t_comm_jitter) == set(b.t_comm_jitter)
            for name, f in a.t_comm_jitter.items():
                assert b.t_comm_jitter[name] == pytest.approx(1.0 / f)


def test_bundled_spec_traces_match_generator(fleet):
    # The committed traces are seeded captures (ROADMAP item 3); pin the
    # recipe so a regenerated file is byte-for-byte the committed one.
    for scenario, seed, path in (
        ("spec_burst", 101, "tests/traces/spec_burst.jsonl"),
        ("spec_flap", 102, "tests/traces/spec_flap.jsonl"),
    ):
        bundled = read_trace(path)
        fresh = generate_trace(scenario, 60, seed=seed, base_fleet=fleet)
        assert [e.model_dump() for e in bundled] == [
            e.model_dump() for e in fresh
        ]


# -- scheduler: default off, probe/serve, donation -------------------------


def test_speculation_off_is_inert(fleet, model):
    trace = generate_trace("spec_flap", 6, seed=5, base_fleet=fleet)
    plain = make_scheduler(fleet, model)
    r1 = replay(plain, trace)
    assert plain.forecaster is None and plain.spec_bank is None
    assert not any(
        k.startswith("spec") for k in plain.metrics.counters
    ), "spec counters leaked into the default path"
    explicit = make_scheduler(fleet, model, speculative=False)
    r2 = replay(explicit, trace)
    assert plain.metrics.counters == explicit.metrics.counters
    for a, b in zip(r1.views, r2.views):
        assert a.mode == b.mode
        assert a.result.model_dump() == b.result.model_dump()


def test_spec_hit_serves_banked_and_donates_warm(fleet, model):
    names = [d.name for d in fleet]
    sched = make_scheduler(fleet, model, speculative=True)
    up = LoadTick(t_comm_jitter={names[1]: 1.4, names[2]: 1.4})
    down = LoadTick(t_comm_jitter={names[1]: 1 / 1.4, names[2]: 1 / 1.4})
    v0 = sched.handle(up)  # cold solve; banks the up-state
    assert v0.mode == "cold"
    # First down-tick is an honest miss: the forecaster's first
    # observation (the up-state) has no previous value to revert to yet.
    v1 = sched.handle(down)
    assert v1.mode == "warm"
    v2 = sched.handle(up)  # the banked up-state (the tick-0 incumbent)
    assert v2.mode == "spec"
    assert v2.result.certified and v2.events_behind == 0
    assert sum(v2.result.w) * v2.result.k == model.L
    v3 = sched.handle(down)  # the banked down-state (the tick-1 solve)
    assert v3.mode == "spec"
    c = sched.metrics.counters
    assert c["spec_hit"] == 2 and c["spec_hit"] + c["spec_miss"] == 4
    assert c["spec_presolve"] >= 1
    assert sched.speculation_snapshot()["hit_rate"] == pytest.approx(2 / 4)
    # Warm donation: the hit installed its scenario solve as the pooled
    # replanner's seed, so the next MISS rides warm, not cold.
    fresh = LoadTick(t_comm_jitter={names[1]: 2.0})
    v3 = sched.handle(fresh)
    assert v3.mode == "warm"
    assert c["tick_cold"] == 1  # only the very first tick paid cold
    # The hit-latency histogram recorded both hits.
    hist = sched.metrics_snapshot()["latency"]["spec_hit_ms"]
    assert hist["count"] == 2
    sched.close()


def test_probe_steps_aside_while_unhealthy(fleet, model):
    names = [d.name for d in fleet]
    sched = make_scheduler(fleet, model, speculative=True, healthy_after=2)
    sched.handle(LoadTick(t_comm_jitter={names[1]: 1.3}))
    sched.handle(LoadTick(t_comm_jitter={names[1]: 1 / 1.3}))
    v = sched.handle(LoadTick(t_comm_jitter={names[1]: 1.3}))
    assert v.mode == "spec"
    assert sched.metrics.counters["spec_hit"] >= 1
    # Poisoned event: quarantined, health degrades — and the forecaster
    # never saw it.
    sched.handle(DeviceDegrade(name=names[1], t_comm_scale=float("nan")))
    assert sched.health != HEALTH_HEALTHY
    fc_state = sched.forecaster.dump_state()
    assert all(
        math.isfinite(v)
        for ch in fc_state["channels"].values()
        for v in ch.values()
    )
    # While degraded, a would-hit event must SOLVE (recovery needs
    # evidence), not serve from the bank.
    probes_before = (
        sched.metrics.counters["spec_hit"]
        + sched.metrics.counters["spec_miss"]
    )
    v = sched.handle(LoadTick(t_comm_jitter={}))
    assert v.mode != "spec"
    assert (
        sched.metrics.counters["spec_hit"]
        + sched.metrics.counters["spec_miss"]
        == probes_before
    )
    # After the clean streak restores health, speculation resumes.
    sched.handle(LoadTick(t_comm_jitter={}))
    assert sched.health == HEALTH_HEALTHY
    v = sched.handle(LoadTick(t_comm_jitter={}))
    assert v.mode == "spec"
    sched.close()


# -- snapshot / restore -----------------------------------------------------


@pytest.mark.parametrize("lp_backend", ["ipm", "pdhg"])
def test_spec_state_rides_snapshot_bit_exact(fleet, model, lp_backend):
    names = [d.name for d in fleet]
    kw = dict(speculative=True, lp_backend=lp_backend)
    sched = make_scheduler([d.model_copy(deep=True) for d in fleet],
                           model, **kw)
    sched.handle(LoadTick(t_comm_jitter={names[1]: 1.35}))
    sched.handle(LoadTick(t_comm_jitter={names[1]: 1 / 1.35}))
    state = sched.dump_state()
    assert state["spec"] is not None
    assert state["spec"]["bank"]["entries"]

    restored = make_scheduler([d.model_copy(deep=True) for d in fleet],
                              model, **kw)
    restored.load_state(json.loads(json.dumps(state)))  # wire trip
    # Bit-exact round trip of the whole speculation block (forecaster
    # EWMA/trend floats and the bank's iterate arrays included).
    assert json.dumps(restored.dump_state()["spec"], sort_keys=True) == (
        json.dumps(state["spec"], sort_keys=True)
    )
    # The first post-restore tick skips the probe (it IS the warm-resume
    # proof): drive an unbanked drift through both schedulers and compare.
    fresh = LoadTick(t_comm_jitter={names[2]: 1.8})
    v_orig = sched.handle(fresh)
    v_rest = restored.handle(fresh)
    assert v_rest.mode == "warm"
    assert restored.metrics.counters["warm_resumes"] == 1
    assert restored.metrics.counters["cold_resumes"] == 0
    assert v_rest.result.model_dump() == v_orig.result.model_dump()
    # ...and the restored bank still hits on a matching later event.
    v = restored.handle(LoadTick(t_comm_jitter={names[2]: 1 / 1.8}))
    assert v.mode == "spec"
    sched.close()
    restored.close()


def test_snapshot_without_spec_block_restores_clean(fleet, model):
    names = [d.name for d in fleet]
    old = make_scheduler([d.model_copy(deep=True) for d in fleet], model)
    old.handle(LoadTick(t_comm_jitter={names[1]: 1.2}))
    state = old.dump_state()
    assert state["spec"] is None  # unspeculative dump carries no block
    new = make_scheduler([d.model_copy(deep=True) for d in fleet], model,
                         speculative=True)
    new.load_state(state)
    assert len(new.spec_bank) == 0 and len(new.forecaster) == 0
    v = new.handle(LoadTick(t_comm_jitter={names[1]: 1.1}))
    assert v.events_behind == 0  # serving works; bank refills from here
    assert len(new.spec_bank) >= 1
    old.close()
    new.close()


# -- chaos interaction ------------------------------------------------------


def test_chaos_soak_reconciles_spec_counters(fleet, model):
    trace = generate_trace("spec_flap", 10, seed=7, base_fleet=fleet)
    plan = FaultPlan(
        seed=3,
        faults=[
            FaultSpec(kind="nan_poison", at_ticks=[2, 6]),
            FaultSpec(kind="malformed_event", at_ticks=[4]),
        ],
    )
    sched = make_scheduler(fleet, model, speculative=True)
    report = chaos_replay(sched, trace, plan)
    assert report.violations(model.L) == []
    c = sched.metrics.counters
    assert c["events_quarantined"] == 3
    assert c["spec_hit"] + c["spec_miss"] > 0
    # The forecaster only ever saw applied events: state finite, channels
    # exactly the live fleet.
    fc = sched.forecaster.dump_state()
    assert set(fc["channels"]) == set(sched.fleet.devices)
    assert all(
        math.isfinite(v)
        for ch in fc["channels"].values()
        for v in ch.values()
    )
    # Tampered counters must trip the reconciliation.
    sched.metrics.counters["spec_hit"] += 5
    bad = report._replace(metrics=sched.metrics_snapshot())
    assert any("speculation accounting" in v for v in bad.violations(model.L))
    sched.close()


def test_many_hits_per_entry_do_not_trip_reconciliation(fleet, model):
    # One banked entry legitimately serves MANY hits (oscillation re-hits
    # the same entry every cycle — the probe never consumes it), so the
    # accounting must stay clean when hits far exceed banked entries.
    trace = generate_trace("spec_flap", 25, seed=13, base_fleet=fleet)
    sched = make_scheduler(fleet, model, speculative=True)
    report = chaos_replay(sched, trace, FaultPlan(seed=0, faults=[]))
    c = sched.metrics.counters
    solved = c["tick_cold"] + c["tick_warm"] + c["tick_margin"]
    assert c["spec_hit"] > c["spec_presolve"] + solved  # the ratio at issue
    assert report.violations(model.L) == []
    sched.close()


def test_failed_tick_reserving_spec_view_reconciles(fleet, model):
    # A solver fault on a MISS tick right after a hit re-serves latest()
    # — a non-quarantined record carrying mode='spec' with events_behind
    # >= 1. The reconciliation must not read that re-serve as a phantom
    # hit, and drift_warm_share must count the spec serve as fast.
    names = [d.name for d in fleet]
    trace = [
        LoadTick(t_comm_jitter={names[1]: 1.4}),
        LoadTick(t_comm_jitter={names[1]: 1 / 1.4}),
        LoadTick(t_comm_jitter={names[1]: 1.4}),  # hit
        LoadTick(t_comm_jitter={names[2]: 2.0}),  # miss -> injected fail
    ]
    plan = FaultPlan(
        seed=1, faults=[FaultSpec(kind="solver_exception", at_ticks=[3])]
    )
    sched = make_scheduler(fleet, model, speculative=True)
    report = chaos_replay(sched, trace, plan)
    c = sched.metrics.counters
    assert c["tick_failed"] == 1 and c["spec_hit"] >= 1
    failed = [
        r for r in report.records
        if r.source == "trace" and r.view.events_behind > 0
    ]
    assert failed and failed[0].view.mode == "spec"  # the re-serve shape
    assert report.violations(model.L) == []
    from distilp_tpu.sched import drift_warm_share

    share = drift_warm_share(sched.metrics)
    assert share >= (c["drift_tick_warm"] + c["drift_tick_spec"]) / max(
        1, c["drift_events"]
    )
    sched.close()


# -- metrics registry / exposition -----------------------------------------


def test_spec_metrics_registered_and_labeled():
    for name in (
        "spec_hit", "spec_miss", "spec_stale", "spec_presolve",
        "spec_presolve_failed", "spec_hit_ms", "spec_presolve_ms",
    ):
        assert registry_help(name) is not None, name
    # Dynamically composed tick-mode counters resolve via the families.
    assert registry_help("drift_tick_spec") is not None
    assert registry_help("structural_tick_spec") is not None
    # Labeled exposition: spec counters render per shard with the full
    # label set and a registered HELP line.
    from distilp_tpu.obs.export import parse_prometheus_text, render_prometheus

    text = render_prometheus(
        [
            {
                "fleet": "f0",
                "shard": "f0::default",
                "worker": 1,
                "health": "healthy",
                "counters": {"spec_hit": 4, "spec_miss": 1},
                "latency": {
                    "spec_hit_ms": {
                        "count": 4, "total_ms": 0.2, "mean_ms": 0.05,
                        "p50_ms": 0.04, "p99_ms": 0.09, "max_ms": 0.09,
                    }
                },
            }
        ]
    )
    assert "unregistered" not in text
    parsed = parse_prometheus_text(text)
    samples = {
        (name, labels.get("fleet"), labels.get("worker"))
        for name, labels, _v in parsed["samples"]
    }
    assert ("distilp_spec_hit", "f0", "1") in samples
    assert any(n == "distilp_spec_hit_ms" for n, _f, _w in samples)

"""Cross-shard solve combiner: batch-layout parity on the golden fixtures,
mixed-M padded buckets with per-instance certificates, warm-state round
trips through batched solves, the committed bucket policy, and the
combiner flush thread."""

import warnings

import pytest

pytest.importorskip("jax")

from distilp_tpu.common import (  # noqa: E402
    load_from_profile_folder,
    load_model_profile,
)
from distilp_tpu.solver import halda_solve  # noqa: E402
from distilp_tpu.utils import make_synthetic_fleet  # noqa: E402

GOLDEN = [
    ("hermes_70b", 40, 29.643569),
    ("llama_3_70b/4bit", 8, 12.834690),
    ("llama_3_70b/online", 2, 1.934942),
    ("qwen3_32b/bf16", 16, 12.072837),
]


def _pack(devs, model, mip_gap, M_pad=None, warm=None, k_candidates=None):
    """One fleet as a (PackedInstance, sets) pair — the test-side analogue
    of ``StreamingReplanner.prepare`` without planner state."""
    from distilp_tpu.solver.api import _build_instance
    from distilp_tpu.solver.batchlayout import pack_instance

    Ks, sets, coeffs, arrays = _build_instance(
        devs, model, k_candidates, "4bit", False, None, 1
    )
    inst = pack_instance(
        arrays,
        [(k, model.L // k) for k in Ks],
        mip_gap=mip_gap,
        coeffs=coeffs,
        warm=warm,
        M_pad=M_pad,
    )
    return inst, sets


class _Ticket:
    """Minimal stand-in for a scheduler CombineTicket: the combiner only
    dereferences ``ticket.prep.instance``."""

    def __init__(self, inst):
        self.prep = type("P", (), {"instance": inst})()


@pytest.mark.parametrize("folder,k_star,obj", GOLDEN)
def test_combined_bucket_matches_golden(profiles_dir, folder, k_star, obj):
    """A golden fixture solved through the combine path — packed at its
    committed bucket boundary (phantom-padded), solved via
    ``_solve_batched``, decoded per-instance — must reproduce the golden
    optimum with a closed certificate, exactly like the per-shard path."""
    from distilp_tpu.combine import BucketPolicy
    from distilp_tpu.solver.api import _best_to_result
    from distilp_tpu.solver.batchlayout import solve_batch

    devs, model = load_from_profile_folder(profiles_dir / folder)
    policy = BucketPolicy()
    inst, sets = _pack(devs, model, 1e-4, M_pad=policy.pad_for(len(devs)))
    assert inst.M_pad >= inst.M_real == len(devs)

    decoded = solve_batch([inst])
    assert len(decoded) == 1
    _, best = decoded[0]
    result = _best_to_result(best, sets)
    assert result.k == k_star
    assert result.obj_value == pytest.approx(obj, rel=2e-4)
    assert result.certified
    assert len(result.w) == len(devs)
    assert sum(result.w) * result.k == model.L


@pytest.mark.slow
def test_combined_matches_per_shard_north_star(profiles_dir):
    """The 16-device north-star instance through a batched solve matches
    the per-shard ``_solve_packed`` result within the certification band."""
    from distilp_tpu.solver.api import _best_to_result
    from distilp_tpu.solver.batchlayout import solve_batch

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(16, seed=123)
    gap = 1e-3
    ref = halda_solve(devs, model, mip_gap=gap, kv_bits="4bit", backend="jax")

    inst, sets = _pack(devs, model, gap, M_pad=16)
    result = _best_to_result(solve_batch([inst])[0][1], sets)
    assert result.certified and result.gap is not None and result.gap <= gap
    assert result.obj_value == pytest.approx(ref.obj_value, rel=2 * gap)
    assert sum(result.w) * result.k == model.L


@pytest.mark.slow
def test_mixed_m_bucket_pads_and_certifies_each_lane(profiles_dir):
    """Three fleets of different sizes share one padded bucket: one
    ``solve_batch`` dispatch, and every lane decodes to its OWN fleet's
    width with its OWN closed certificate matching its per-shard solve."""
    from distilp_tpu.solver.batchlayout import solve_batch

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    gap = 1e-3
    # A fixed k grid (every W >= the largest fleet) keeps the feasibility
    # filter from shrinking n_k for the M=8 fleet — the gateway's shards
    # share k_candidates the same way.
    ks = [8, 10]
    fleets = [make_synthetic_fleet(M, seed=s) for M, s in [(4, 4), (6, 7), (8, 8)]]
    packed = [_pack(devs, model, gap, M_pad=8, k_candidates=ks) for devs in fleets]
    insts = [inst for inst, _ in packed]
    assert len({inst.signature for inst in insts}) == 1, (
        "mixed-M fleets padded to one boundary must share a bucket"
    )

    tm = {}
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        decoded = solve_batch(insts, timings=tm)
    assert tm["batch_size"] == 3
    for devs, (inst, _), (per_k, best) in zip(fleets, packed, decoded):
        assert best is not None and best.certified
        assert best.gap is not None and best.gap <= gap
        assert len(best.w) == inst.M_real == len(devs)
        assert sum(best.w) * best.k == model.L
        # Per-instance certificate decode: the lane's per-k entries are
        # its own sweep, not a batch-level aggregate.
        assert len(per_k) == len(inst.kWs)
        ref = halda_solve(
            devs, model, mip_gap=gap, kv_bits="4bit", backend="jax",
            k_candidates=ks,
        )
        assert best.obj_value == pytest.approx(ref.obj_value, rel=2 * gap)


@pytest.mark.slow
def test_lane_padding_duplicates_solve_identically(profiles_dir):
    """``lane_pad`` (the combiner's committed lane quantization) repeats
    the last instance to a fixed lane count without changing any real
    lane's decode."""
    from distilp_tpu.solver.batchlayout import solve_batch

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    gap = 1e-3
    insts = [
        _pack(make_synthetic_fleet(M, seed=s), model, gap, M_pad=8,
              k_candidates=[8, 10])[0]
        for M, s in [(4, 4), (6, 7), (8, 8)]
    ]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        plain = solve_batch(insts)
        padded = solve_batch(insts, lane_pad=4)
    assert len(plain) == len(padded) == 3
    for (_, a), (_, b) in zip(plain, padded):
        assert a.obj_value == pytest.approx(b.obj_value, abs=0.0)
        assert a.w == b.w and a.n == b.n and a.k == b.k
    with pytest.raises(ValueError, match="lane_pad"):
        solve_batch(insts, lane_pad=2)


def test_lane_static_cache_survives_membership_churn(profiles_dir):
    """The per-lane static device cache: a repeat flush — and a REORDERED
    flush, which the whole-stack cache could never hit — re-ships zero
    static bytes (``static_hit == 1.0``) and decodes identically. This is
    the combine analogue of the per-shard warm-tick wire-cost contract:
    bucket membership churn must not re-upload drift-invariant halves."""
    from distilp_tpu.solver.batchlayout import (
        clear_lane_static_cache,
        solve_batch,
    )

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    insts = [
        _pack(make_synthetic_fleet(4, seed=s), model, 1e-3, M_pad=4,
              k_candidates=[8, 10])[0]
        for s in (1, 2, 3)
    ]
    clear_lane_static_cache()
    tm1, tm2, tm3 = {}, {}, {}
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        d1 = solve_batch(insts, timings=tm1, lane_pad=4)
        d2 = solve_batch(insts, timings=tm2, lane_pad=4)
        d3 = solve_batch(list(reversed(insts)), timings=tm3, lane_pad=4)
    # First contact uploads the three distinct lanes; the duplicated pad
    # lane (same bytes as lane 3) already hits within the same flush.
    assert tm1["static_hit"] == pytest.approx(0.25)
    assert tm2["static_hit"] == 1.0
    assert tm3["static_hit"] == 1.0
    for (_, a), (_, b), (_, c) in zip(d1, d2, reversed(d3)):
        assert a.obj_value == pytest.approx(b.obj_value, abs=0.0)
        assert a.obj_value == pytest.approx(c.obj_value, abs=0.0)
        assert a.w == b.w == c.w
    # Validation happens before any dispatch: a lane_pad below the batch
    # size must raise, never silently truncate lanes.
    with pytest.raises(ValueError, match="lane_pad"):
        solve_batch(insts, lane_pad=2)


@pytest.mark.slow
def test_warm_roundtrip_through_batched_solve_bit_exact(profiles_dir):
    """A replanner whose warm state came from an adopted BATCHED solve
    dump/load round-trips bit-exactly, and the restored replanner's next
    combined tick packs the identical instance."""
    from distilp_tpu.solver.batchlayout import solve_batch
    from distilp_tpu.solver.streaming import StreamingReplanner

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(5, seed=3)
    planner = StreamingReplanner(mip_gap=1e-3, kv_bits="4bit", backend="jax")

    # Tick 1 per-shard (the warmup path), tick 2 combined.
    planner.step(devs, model)
    devs[2].t_comm *= 1.05
    prep = planner.prepare(devs, model, M_pad=8)
    assert prep is not None and prep.warm_used
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        result = planner.adopt(prep, solve_batch([prep.instance])[0])
    assert result.certified
    assert planner.last is result

    blob = planner.dump_warm_state()
    restored = StreamingReplanner(mip_gap=1e-3, kv_bits="4bit", backend="jax")
    restored.load_warm_state(blob)
    assert restored.dump_warm_state() == blob  # bit-exact round trip

    # Same fleet state in, same packed bytes out: the restored replanner's
    # combined tick is indistinguishable from the uninterrupted one's.
    import numpy as np

    prep_a = planner.prepare(devs, model, M_pad=8)
    prep_b = restored.prepare(devs, model, M_pad=8)
    assert prep_a.instance.signature == prep_b.instance.signature
    assert np.array_equal(prep_a.instance.static_np, prep_b.instance.static_np)
    # equal_nan: unused dual/warm slots are NaN sentinels by design.
    assert np.array_equal(
        prep_a.instance.dyn_np, prep_b.instance.dyn_np, equal_nan=True
    )


def test_scheduler_prepare_adopt_publishes_combine_mode(profiles_dir):
    """The scheduler halves of a combined tick: ``prepare_combine`` packs
    a ticket (no view), ``adopt_combine`` publishes mode='combine' with
    the same counters/flight side effects as a local tick, and a stale
    ticket (fleet advanced past it) is discarded, not adopted."""
    from distilp_tpu.sched.events import DeviceDegrade
    from distilp_tpu.sched.scheduler import Scheduler
    from distilp_tpu.solver.batchlayout import solve_batch

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(4, seed=4)
    sched = Scheduler(
        devs, model, mip_gap=1e-3, kv_bits="4bit", backend="jax",
        speculative=False,
    )
    # Warm up per-shard first — the gateway does the same before flipping
    # admission into combine mode.
    sched.handle(DeviceDegrade(name=devs[0].name, t_comm_scale=1.01))

    ev = DeviceDegrade(name=devs[0].name, t_comm_scale=1.02)
    ticket, view = sched.prepare_combine([ev], M_pad=4)
    assert view is None and ticket is not None
    assert sched.metrics.counters.get("combine_prepared") == 1

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        decoded = solve_batch([ticket.prep.instance])[0]
    out = sched.adopt_combine(ticket, decoded)
    assert out.mode == "combine"
    assert out.result.certified
    assert sched.latest().mode == "combine"

    # Stale ticket: the fleet moved on (another event applied) before the
    # batch landed — the decoded lane must be discarded, never published.
    ticket2, view2 = sched.prepare_combine(
        [DeviceDegrade(name=devs[1].name, t_comm_scale=1.01)], M_pad=4
    )
    assert ticket2 is not None and view2 is None
    sched.handle(DeviceDegrade(name=devs[2].name, t_comm_scale=1.01))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        decoded2 = solve_batch([ticket2.prep.instance])[0]
    served = sched.adopt_combine(ticket2, decoded2)
    assert sched.metrics.counters.get("combine_stale") == 1
    # The served view is the newer local tick's publication, not the stale
    # lane: its solve seq is past the ticket's.
    assert served.mode != "combine"
    assert served.seq > ticket2.seq


def test_bucket_policy_contract():
    """The committed policy: boundary snapping, lane caps under a memory
    budget, power-of-two lane quantization, and validation."""
    from distilp_tpu.combine import BucketPolicy
    from distilp_tpu.ops.memmodel import peak_bytes

    p = BucketPolicy()
    assert p.pad_for(1) == 2
    assert p.pad_for(5) == 8
    assert p.pad_for(128) == 128
    assert p.pad_for(200) == 200  # above the top boundary: exact M
    with pytest.raises(ValueError):
        p.pad_for(0)

    # Lane quantization: powers of two, clamped to the cap.
    assert p.quantize_lanes(1, 8) == 1
    assert p.quantize_lanes(3, 8) == 4
    assert p.quantize_lanes(5, 8) == 8
    assert p.quantize_lanes(16, 8) == 16
    assert p.lane_shapes(8) == (1, 2, 4, 8, 16)

    # A memory budget prices lanes via the analytic model at the PADDED M.
    budget = 3 * peak_bytes(16, "ipm")
    tight = BucketPolicy(mem_budget_bytes=budget)
    assert tight.lane_cap(16) == 3
    assert tight.lane_cap(128) == 1  # never below one lane
    assert tight.quantize_lanes(2, 16) == 2
    assert tight.quantize_lanes(3, 16) == 3  # cap overrides the pow2 snap
    assert tight.lane_shapes(16) == (1, 2, 3)

    with pytest.raises(ValueError):
        BucketPolicy(boundaries=(4, 2))
    with pytest.raises(ValueError):
        BucketPolicy(max_batch=0)
    with pytest.raises(ValueError):
        BucketPolicy(max_wait_ms=-1.0)


def test_combiner_thread_semantics_with_stub_solver(profiles_dir, monkeypatch):
    """Tier-1 half of the flush-thread contract — bucketing by signature,
    exactly-once delivery, drain on stop, post-stop fail-fast — with the
    batched solver stubbed out so no executable is minted. The slow twin
    below runs the identical protocol through real solves; the combiner
    itself never inspects decoded lanes, so the thread semantics are
    fully exercised here."""
    import threading

    from distilp_tpu.combine import BucketPolicy, CombineEntry, SolveCombiner
    from distilp_tpu.solver import batchlayout as bl

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    ks = [8, 10]
    insts = [
        _pack(make_synthetic_fleet(4, seed=4), model, 1e-3, M_pad=8,
              k_candidates=ks)[0],
        _pack(make_synthetic_fleet(6, seed=7), model, 1e-3, M_pad=8,
              k_candidates=ks)[0],
        _pack(make_synthetic_fleet(4, seed=4), model, 1e-3, M_pad=4,
              k_candidates=ks)[0],
    ]
    assert insts[0].signature == insts[1].signature != insts[2].signature

    calls = []

    def _stub_solve_batch(batch, timings=None, lane_pad=None):
        calls.append(len(batch))
        assert len({i.signature for i in batch}) == 1, (
            "a flush must never mix signatures"
        )
        if timings is not None:
            timings.update(batch_size=len(batch), static_hit=1.0)
        return [("stub", None) for _ in batch]

    # _flush imports solve_batch from the module at call time, so the
    # module attribute is the patch point.
    monkeypatch.setattr(bl, "solve_batch", _stub_solve_batch)

    got = {}
    done = threading.Event()

    def deliver(i):
        def _d(decoded, err):
            got[i] = (decoded, err)
            if len(got) == 3:
                done.set()
        return _d

    combiner = SolveCombiner(BucketPolicy(max_wait_ms=20.0))
    try:
        for i, inst in enumerate(insts):
            combiner.submit(CombineEntry(_Ticket(inst), deliver(i)))
        assert done.wait(timeout=60.0), f"undelivered: {set(got)}"
    finally:
        combiner.stop()

    for i in range(3):
        decoded, err = got[i]
        assert err is None and decoded == ("stub", None)

    snap = combiner.snapshot()
    assert snap["instances"] == 3
    assert snap["batches"] == len(calls) == 2  # one flush per signature
    assert sorted(calls) == [1, 2]  # the shared-sig pair rode together
    assert snap["pending"] == 0 and snap["errors"] == 0

    # Post-stop submits deliver an error immediately instead of queueing.
    late = {}
    combiner.submit(
        CombineEntry(_Ticket(insts[0]), lambda d, e: late.update(err=e))
    )
    assert isinstance(late.get("err"), RuntimeError)


@pytest.mark.slow
def test_combiner_buckets_by_signature_and_drains_on_stop(profiles_dir):
    """The flush thread: same-signature lanes batch together, different
    signatures never share a dispatch, every submitted lane is delivered
    exactly once (stop() drains), and post-stop submits fail fast."""
    import threading

    from distilp_tpu.combine import BucketPolicy, CombineEntry, SolveCombiner

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    gap = 1e-3

    # Two buckets: two fleets padded to 8 (shared sig), one at 4.
    ks = [8, 10]
    insts = [
        _pack(make_synthetic_fleet(4, seed=4), model, gap, M_pad=8,
              k_candidates=ks)[0],
        _pack(make_synthetic_fleet(6, seed=7), model, gap, M_pad=8,
              k_candidates=ks)[0],
        _pack(make_synthetic_fleet(4, seed=4), model, gap, M_pad=4,
              k_candidates=ks)[0],
    ]
    assert insts[0].signature == insts[1].signature != insts[2].signature

    got = {}
    done = threading.Event()

    def deliver(i):
        def _d(decoded, err):
            got[i] = (decoded, err)
            if len(got) == 3:
                done.set()
        return _d

    combiner = SolveCombiner(BucketPolicy(max_wait_ms=50.0))
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for i, inst in enumerate(insts):
                combiner.submit(CombineEntry(_Ticket(inst), deliver(i)))
            assert done.wait(timeout=300.0), f"undelivered: {set(got)}"
    finally:
        combiner.stop()

    for i, inst in enumerate(insts):
        decoded, err = got[i]
        assert err is None
        _, best = decoded
        assert best is not None and best.certified
        assert len(best.w) == inst.M_real

    snap = combiner.snapshot()
    assert snap["instances"] == 3
    assert snap["batches"] == 2  # one per signature
    assert snap["pending"] == 0 and snap["errors"] == 0

    # Post-stop submits deliver an error immediately instead of queueing.
    late = {}
    combiner.submit(
        CombineEntry(_Ticket(insts[0]), lambda d, e: late.update(err=e))
    )
    assert isinstance(late.get("err"), RuntimeError)

"""Scheduler service: events, fleet state, warm-pooled replanning, metrics.

Solver-backed tests run the JAX backend on CPU with a small L=32 model and
a restricted k-grid so each tick after jit warmup is milliseconds; the
distinct fleet shapes (and thus compiles) are kept to a handful.
"""

from __future__ import annotations

import json

import pytest

from distilp_tpu.sched import (
    DeviceDegrade,
    DeviceJoin,
    DeviceLeave,
    FleetState,
    LoadTick,
    Scheduler,
    drift_warm_share,
    generate_trace,
    is_structural,
    read_trace,
    replay,
    write_trace,
)
from distilp_tpu.sched.metrics import LatencyHist
from distilp_tpu.utils import make_synthetic_fleet

GAP = 1e-3
KS = [4, 8]  # proper factors of L=32; W=8,4 keeps small fleets feasible


@pytest.fixture(scope="module")
def model():
    from distilp_tpu.profiler.api import profile_model

    return profile_model(
        "tests/configs/llama31_8b_4bit.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()


@pytest.fixture()
def fleet():
    return make_synthetic_fleet(4, seed=11)


def make_scheduler(fleet, model, **kw):
    kw.setdefault("mip_gap", GAP)
    kw.setdefault("kv_bits", "4bit")
    kw.setdefault("backend", "jax")
    kw.setdefault("k_candidates", KS)
    return Scheduler(fleet, model, **kw)


# -- events + trace format (no solver) ------------------------------------


def test_trace_jsonl_roundtrip(tmp_path, fleet):
    trace = generate_trace("mixed", 40, seed=3, base_fleet=fleet)
    path = tmp_path / "trace.jsonl"
    write_trace(path, trace)
    back = read_trace(path)
    assert len(back) == len(trace)
    for a, b in zip(trace, back):
        assert type(a) is type(b)
        assert a.model_dump() == b.model_dump()
    # Generation itself is seed-deterministic, event for event.
    again = generate_trace("mixed", 40, seed=3, base_fleet=fleet)
    assert [e.model_dump() for e in again] == [e.model_dump() for e in trace]
    # Scenario mix covers the advertised churn classes, including the
    # bandwidth-decay degrade flavor (not just t_comm jitter).
    kinds = {e.kind for e in trace}
    assert "join" in kinds or "leave" in kinds
    assert kinds & {"degrade", "load"}
    assert any(
        e.kind == "degrade" and e.bandwidth_scale != 1.0 for e in trace
    )


def test_fleet_apply_semantics(fleet, model):
    fs = FleetState(fleet, model)
    names = [d.name for d in fleet]
    base_key = fs.key()

    # Drift: digest stable, coefficients move.
    t0 = fs.devices[names[1]].t_comm
    assert fs.apply(DeviceDegrade(name=names[1], t_comm_scale=1.5)) is False
    assert fs.devices[names[1]].t_comm == pytest.approx(t0 * 1.5)
    assert fs.key() == base_key

    # Memory degrade shrinks every advertised pool; bandwidth decay scales
    # the measured link rate (when the profile carries one).
    ram0 = fs.devices[names[2]].d_avail_ram
    fs.devices[names[2]].comm_bandwidth = 1e9
    fs.apply(DeviceDegrade(name=names[2], mem_scale=0.5, bandwidth_scale=0.9))
    assert fs.devices[names[2]].d_avail_ram == int(ram0 * 0.5)
    assert fs.devices[names[2]].comm_bandwidth == pytest.approx(0.9e9)

    # Leave of the head promotes the next device; digest changes.
    assert fs.apply(DeviceLeave(name=names[0])) is True
    assert fs.key() != base_key
    assert fs.device_list()[0].is_head
    assert sum(d.is_head for d in fs.device_list()) == 1

    # Join lands at the tail, never as head.
    joiner = make_synthetic_fleet(1, seed=99)[0]
    joiner.name = "joiner-0"
    joiner.is_head = True  # must be demoted on entry
    fs.apply(DeviceJoin(device=joiner))
    assert fs.device_list()[-1].name == "joiner-0"
    assert not fs.device_list()[-1].is_head

    # Strictness: malformed events are errors, not silent no-ops.
    with pytest.raises(ValueError):
        fs.apply(DeviceLeave(name="nobody"))
    with pytest.raises(ValueError):
        fs.apply(DeviceJoin(device=joiner))  # duplicate name
    with pytest.raises(ValueError):
        fs.apply(LoadTick(t_comm_jitter={"nobody": 1.1}))

    # seq counts successfully applied events (rejected ones don't count).
    assert fs.seq == 4


def test_latency_hist_quantiles():
    h = LatencyHist()
    for v in range(1, 101):
        h.record(float(v))
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["p50_ms"] == pytest.approx(50.0, abs=1.0)
    assert snap["p99_ms"] == pytest.approx(99.0, abs=1.0)
    assert snap["max_ms"] == 100.0
    # Below the cap the two views coincide.
    assert snap["window_count"] == 100
    assert snap["window_mean_ms"] == snap["mean_ms"]
    assert json.dumps(snap)  # plain types only


def test_latency_hist_windowed_snapshot_after_overflow():
    """Post-overflow coherence: all-time fields keep counting while the
    quantiles/max/window_* describe only the cap-bounded recent window —
    the snapshot says WHICH population each number comes from instead of
    silently mixing them (the old mean_ms was all-time next to windowed
    p50/p99)."""
    h = LatencyHist(cap=4)
    for v in range(1, 11):  # 1..10; window keeps 7,8,9,10
        h.record(float(v))
    snap = h.snapshot()
    assert snap["count"] == 10
    assert snap["mean_ms"] == pytest.approx(5.5)  # all-time
    assert snap["window_count"] == 4
    assert snap["window_mean_ms"] == pytest.approx(8.5)  # recent window
    # Quantiles/max come from the SAME window the window_mean describes.
    assert snap["p50_ms"] == pytest.approx(9.0, abs=1.0)
    assert snap["p99_ms"] == 10.0
    assert snap["max_ms"] == 10.0
    assert json.dumps(snap)


# -- the replanning core (JAX backend on CPU) ------------------------------


def test_50_event_churn_acceptance(fleet, model):
    """The acceptance trace: 50 seeded churn events (joins, leaves,
    bandwidth decay, load drift) replay end-to-end; every structural event
    yields a certified placement; drift rides warm/margin ticks; the
    metrics snapshot agrees with the tick modes — and a second scheduler
    replaying the same trace reproduces the placement sequence exactly."""
    trace = generate_trace("mixed", 50, seed=23, base_fleet=fleet)
    assert len(trace) == 50

    sched = make_scheduler([d.model_copy(deep=True) for d in fleet], model)
    report = replay(sched, trace)
    assert report.failed_ticks == 0
    assert report.structural_uncertified == 0
    for ev, view in zip(trace, report.views):
        if is_structural(ev):
            assert view.result.certified, f"uncertified structural {ev.kind}"
        assert view.events_behind == 0  # every event produced a placement
        assert sum(view.result.w) * view.result.k == model.L

    # Drift events must ride the streaming fast paths.
    assert drift_warm_share(sched.metrics) >= 0.6

    # Metrics agree with tick modes over the whole trace.
    c = sched.metrics.counters
    assert c["events_total"] == 50
    assert c["structural_events"] + c["drift_events"] == 50
    assert sched.metrics.tick_total() == 50 - c["tick_failed"]
    assert c["tick_certified"] == 50
    assert c["tick_uncertified"] == 0
    # Mode split per routing class adds back up to the global mode counts.
    for mode in ("cold", "warm", "margin"):
        assert (
            c[f"structural_tick_{mode}"] + c[f"drift_tick_{mode}"]
            == c[f"tick_{mode}"]
        )
    # Latency histograms saw every tick.
    snap = sched.metrics_snapshot()
    assert snap["latency"]["event_to_placement"]["count"] == 50
    assert json.dumps(snap)  # plain-dict contract

    # Determinism: same trace, fresh scheduler => identical placements.
    sched2 = make_scheduler([d.model_copy(deep=True) for d in fleet], model)
    report2 = replay(sched2, trace)
    seq1 = [
        (v.result.k, tuple(v.result.w), tuple(v.result.n), v.result.obj_value)
        for v in report.views
    ]
    seq2 = [
        (v.result.k, tuple(v.result.w), tuple(v.result.n), v.result.obj_value)
        for v in report2.views
    ]
    assert seq1 == seq2


def test_warm_pool_eviction_keeps_serving(fleet, model):
    """Pool capacity 1: every identity change evicts the previous warm
    replanner. Correctness must not care — evicted identities re-solve
    cold and still certify."""
    trace = generate_trace("flap", 14, seed=5, base_fleet=fleet)
    assert any(e.kind == "leave" for e in trace)
    sched = make_scheduler(
        [d.model_copy(deep=True) for d in fleet], model, warm_pool_size=1
    )
    report = replay(sched, trace)
    c = sched.metrics.counters
    assert c["pool_evict"] >= 2
    assert len(sched.pool) == 1
    assert report.failed_ticks == 0
    assert all(v.result.certified for v in report.views)
    # Flapped-back identities were NOT warm (capacity 1 evicted them), so
    # structural ticks all ran cold — the pool trades speed, not answers.
    assert c["structural_tick_warm"] == 0


def test_degrade_event_triggers_recertification(fleet, model):
    """A degrade event must produce a freshly certified placement (not a
    stale serve): the tick runs warm and re-certifies under the degraded
    coefficients."""
    sched = make_scheduler([d.model_copy(deep=True) for d in fleet], model)
    first = sched.handle(LoadTick(t_comm_jitter={}))  # initial cold solve
    assert first.result.certified and first.mode == "cold"

    target = fleet[2].name
    view = sched.handle(DeviceDegrade(name=target, t_comm_scale=2.0))
    assert view.events_behind == 0  # a new placement was published
    assert view.result.certified
    assert view.mode == "warm"  # same identity -> warm fast path
    c = sched.metrics.counters
    assert c["drift_tick_warm"] == 1
    assert c["tick_certified"] == 2

    # The degraded link is priced in: solving the degraded fleet cold
    # agrees with the warm tick's objective.
    from distilp_tpu.solver import halda_solve

    cold = halda_solve(
        sched.fleet.device_list(), model, k_candidates=KS,
        mip_gap=GAP, kv_bits="4bit", backend="jax",
    )
    assert abs(view.result.obj_value - cold.obj_value) <= (
        2 * GAP * abs(cold.obj_value) + 1e-9
    )


def test_failed_tick_serves_stale(fleet, model):
    """An event that makes the instance infeasible (fleet outgrows the
    k-grid) must not take the service down: the tick fails, the previous
    placement stays served, staleness is visible."""
    sched = make_scheduler(
        [d.model_copy(deep=True) for d in fleet], model, k_candidates=[8]
    )  # k=8 -> W=4: feasible at M=4, infeasible at M=5
    ok = sched.handle(LoadTick(t_comm_jitter={}))
    assert ok.result.certified

    joiner = make_synthetic_fleet(1, seed=77)[0]
    joiner.name = "late-joiner"
    view = sched.handle(DeviceJoin(device=joiner))
    # The returned view is the STALE placement, one event behind.
    assert view.events_behind == 1
    assert view.result.k == ok.result.k
    assert sched.metrics.counters["tick_failed"] == 1
    assert sched.metrics.counters["tick_failed_structural"] == 1
    later = sched.latest()
    assert later.events_behind == 1
    assert later.seq == ok.seq


def test_moe_drift_ticks_ride_margin_path():
    """MoE identity: scheduler drift ticks engage the margin fast path and
    the metrics record them as margin ticks (the dense tests above can
    only ever see cold/warm)."""
    from distilp_tpu.profiler.api import profile_model

    moe_model = profile_model(
        "tests/configs/mixtral_8x7b.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    sched = Scheduler(
        devs, moe_model, mip_gap=GAP, kv_bits="8bit", backend="jax"
    )
    names = [d.name for d in devs]
    first = sched.handle(DeviceDegrade(name=names[1], t_comm_scale=1.01))
    assert first.result.certified and first.result.y is not None

    for scale in (1.03, 0.98):
        view = sched.handle(DeviceDegrade(name=names[2], t_comm_scale=scale))
        assert view.result.certified
        assert view.mode == "margin"
    c = sched.metrics.counters
    assert c["drift_tick_margin"] == 2
    assert c["tick_margin"] == 2
    # 3 drift events: the bootstrap cold tick + 2 margin ticks.
    assert drift_warm_share(sched.metrics) == pytest.approx(2 / 3)

"""Chaos hardening: fault injection, quarantine, deadlines, the circuit
breaker, health recovery, and the in-solver certification escalation.

Solver-backed tests reuse the test_sched setup (small L=32 model, 4
synthetic devices, restricted k-grid) so each post-compile tick is
milliseconds. The breaker/deadline state machines are driven through the
scheduler's ``fault_hook`` seam — the same seam ``chaos_replay`` uses — so
what the unit tests pin is exactly what the chaos soak exercises.
"""

from __future__ import annotations

import time

import pytest

pytest.importorskip("jax")

from distilp_tpu.sched import (  # noqa: E402
    HEALTH_BROKEN,
    HEALTH_DEGRADED,
    HEALTH_HEALTHY,
    DeviceDegrade,
    DeviceJoin,
    DeviceLeave,
    FaultInjector,
    FaultPlan,
    FaultSpec,
    LoadTick,
    Scheduler,
    chaos_replay,
    generate_trace,
    replay,
)
from distilp_tpu.sched.events import validate_event  # noqa: E402
from distilp_tpu.utils import make_synthetic_fleet  # noqa: E402

GAP = 1e-3
KS = [4, 8]  # proper factors of L=32


@pytest.fixture(scope="module")
def model():
    from distilp_tpu.profiler.api import profile_model

    return profile_model(
        "tests/configs/llama31_8b_4bit.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()


@pytest.fixture()
def fleet():
    return make_synthetic_fleet(4, seed=11)


def make_scheduler(fleet, model, **kw):
    kw.setdefault("mip_gap", GAP)
    kw.setdefault("kv_bits", "4bit")
    kw.setdefault("backend", "jax")
    kw.setdefault("k_candidates", KS)
    return Scheduler([d.model_copy(deep=True) for d in fleet], model, **kw)


# -- the injector (no solver) ----------------------------------------------


def test_fault_plan_schedule_deterministic():
    plan = FaultPlan(
        seed=42,
        faults=[
            FaultSpec(kind="solver_exception", p=0.25, start=0, end=50),
            FaultSpec(kind="nan_poison", p=0.1, start=10, end=40),
            FaultSpec(kind="dropout_burst", at_ticks=[7, 31]),
        ],
    )
    s1 = FaultInjector(plan).schedule(50)
    s2 = FaultInjector(plan).schedule(50)
    assert s1 == s2 and len(s1) > 4  # same seed -> identical schedule
    other = FaultInjector(plan.model_copy(update={"seed": 43})).schedule(50)
    assert other != s1  # the seed is load-bearing
    # Windows are honored: no probabilistic fault outside [start, end).
    assert all(10 <= t < 40 for t, k in s1 if k == "nan_poison")
    assert [t for t, k in s1 if k == "dropout_burst"] == [7, 31]


def test_validate_event_catches_poison_and_contradiction(fleet):
    assert validate_event(DeviceDegrade(name="x", t_comm_scale=float("nan")))
    assert validate_event(DeviceDegrade(name="x", t_comm_scale=-2.0))
    assert validate_event(DeviceDegrade(name="x", mem_scale=-0.1))
    assert validate_event(LoadTick(t_comm_jitter={"a": float("inf")}))
    assert validate_event(LoadTick(expert_loads=[1.0, float("nan")]))
    assert validate_event(LoadTick(expert_loads=[0.0, 0.0]))
    bad_dev = fleet[1].model_copy(deep=True)
    bad_dev.T_cpu = float("inf")
    assert validate_event(DeviceJoin(device=bad_dev))
    # Sane events pass.
    assert validate_event(DeviceDegrade(name="x", t_comm_scale=1.2)) is None
    assert validate_event(LoadTick(t_comm_jitter={"a": 0.97})) is None
    assert validate_event(DeviceJoin(device=fleet[1])) is None
    assert validate_event(DeviceLeave(name="x")) is None


# -- quarantine through the scheduler --------------------------------------


def test_nan_poisoned_events_are_quarantined(fleet, model):
    sched = make_scheduler(fleet, model)
    first = sched.handle(LoadTick(t_comm_jitter={}))
    assert first.result.certified and sched.health == HEALTH_HEALTHY

    target = fleet[2].name
    t_before = sched.fleet.devices[target].t_comm
    seq_before = sched.fleet.seq

    view = sched.handle(DeviceDegrade(name=target, t_comm_scale=float("nan")))
    # Fleet untouched, previous placement still served, fault accounted.
    assert sched.fleet.devices[target].t_comm == t_before
    assert sched.fleet.seq == seq_before
    assert view.result is first.result
    c = sched.metrics.counters
    assert c["events_quarantined"] == 1
    assert c["quarantine_degrade"] == 1
    assert sched.health == HEALTH_DEGRADED
    assert sched.quarantined and "non-finite" in sched.quarantined[-1][2]

    # A join carrying a poisoned profile is rejected the same way.
    bad = fleet[1].model_copy(deep=True)
    bad.name = "poisoned-joiner"
    bad.T_cpu = float("inf")
    sched.handle(DeviceJoin(device=bad))
    assert "poisoned-joiner" not in sched.fleet.devices
    assert c["events_quarantined"] == 2

    # Malformed events (strict-apply rejections) quarantine too.
    sched.handle(DeviceLeave(name="nobody"))
    assert c["events_quarantined"] == 3
    assert c["quarantine_leave"] == 1

    # Clean ticks recover health (healthy_after defaults to 3).
    for _ in range(3):
        sched.handle(LoadTick(t_comm_jitter={}))
    assert sched.health == HEALTH_HEALTHY
    assert c["health_recovered"] == 1


def test_loadtick_quarantine_leaves_fleet_untouched(fleet, model):
    """Quarantine atomicity: a LoadTick naming one unknown device must not
    half-apply (mutating the known devices' t_comm or expert_loads before
    the rejection) — the quarantine record claims the fleet was untouched,
    and a half-applied event would make the state unreproducible."""
    sched = make_scheduler(fleet, model)
    sched.handle(LoadTick(t_comm_jitter={}))
    known = fleet[1].name
    t_before = sched.fleet.devices[known].t_comm
    loads_before = sched.fleet.model.expert_loads
    sched.handle(
        LoadTick(
            t_comm_jitter={known: 1.5, "ghost-device": 1.2},
            expert_loads=[1.0, 1.0, 1.0, 1.0],
        )
    )
    assert sched.fleet.devices[known].t_comm == t_before
    assert sched.fleet.model.expert_loads == loads_before
    assert sched.metrics.counters["events_quarantined"] == 1
    assert sched.metrics.counters["quarantine_load"] == 1


def test_poisoned_event_before_first_placement_raises(fleet, model):
    sched = make_scheduler(fleet, model)
    with pytest.raises(ValueError, match="poisoned"):
        sched.handle(DeviceDegrade(name=fleet[1].name, t_comm_scale=float("nan")))


# -- retries, breaker, health ----------------------------------------------


class _Hook:
    """A controllable fault_hook: fails attempts while ``failing``."""

    def __init__(self, transient=False):
        self.failing = False
        self.transient = transient
        self.calls = 0

    def __call__(self, attempt):
        self.calls += 1
        if self.failing and not (self.transient and attempt > 0):
            raise RuntimeError("injected by _Hook")


def test_retry_ladder_saves_transient_faults(fleet, model):
    hook = _Hook(transient=True)
    sched = make_scheduler(
        fleet, model, max_retries=2, retry_backoff_s=0.001, fault_hook=hook
    )
    sched.handle(LoadTick(t_comm_jitter={}))
    hook.failing = True
    view = sched.handle(DeviceDegrade(name=fleet[1].name, t_comm_scale=1.1))
    hook.failing = False
    # Attempt 0 failed, attempt 1 succeeded: a fresh placement was served.
    assert view.events_behind == 0
    c = sched.metrics.counters
    assert c["solve_retries"] == 1
    assert c["solve_retry_success"] == 1
    assert c["tick_failed"] == 0


def test_breaker_open_half_open_close(fleet, model):
    hook = _Hook()
    sched = make_scheduler(
        fleet,
        model,
        breaker_threshold=2,
        breaker_cooldown=2,
        healthy_after=2,
        fault_hook=hook,
    )
    sched.handle(LoadTick(t_comm_jitter={}))  # publish a placement
    c = sched.metrics.counters

    # Two consecutive failures open the breaker.
    hook.failing = True
    sched.handle(LoadTick(t_comm_jitter={}))
    assert sched.health == HEALTH_DEGRADED
    sched.handle(LoadTick(t_comm_jitter={}))
    assert c["breaker_open"] == 1
    assert sched.health == HEALTH_BROKEN

    # Cooldown: two ticks serve degraded without touching the solver.
    calls_before = hook.calls
    v1 = sched.handle(LoadTick(t_comm_jitter={}))
    v2 = sched.handle(LoadTick(t_comm_jitter={}))
    assert hook.calls == calls_before  # no solve attempts at all
    assert c["breaker_short_circuit"] == 2
    assert v1.mode == v2.mode == "degraded"
    assert v2.events_behind > 0

    # Half-open probe fails -> re-open, full cooldown again.
    sched.handle(LoadTick(t_comm_jitter={}))
    assert c["breaker_half_open_probe"] == 1
    assert c["breaker_reopen"] == 1
    assert sched.health == HEALTH_BROKEN

    # Let the cooldown drain, then a successful probe closes the breaker.
    hook.failing = False
    sched.handle(LoadTick(t_comm_jitter={}))
    sched.handle(LoadTick(t_comm_jitter={}))
    assert c["breaker_short_circuit"] == 4
    probe = sched.handle(LoadTick(t_comm_jitter={}))
    assert c["breaker_half_open_probe"] == 2
    assert c["breaker_close"] == 1
    assert probe.events_behind == 0  # the probe's fresh solve is served
    assert sched.health == HEALTH_DEGRADED  # not yet: streak must clear it
    sched.handle(LoadTick(t_comm_jitter={}))
    assert sched.health == HEALTH_HEALTHY
    snap = sched.health_snapshot()
    assert snap["state"] == "healthy" and snap["breaker_open"] is False


def test_deadline_miss_serves_stale_and_recovers(fleet, model):
    hook = _Hook()
    sched = make_scheduler(
        fleet, model, solve_deadline_s=0.08, fault_hook=hook
    )
    first = sched.handle(LoadTick(t_comm_jitter={}))  # exempt first solve
    assert first.events_behind == 0

    # A latency spike sleeping past the deadline inside the attempt.
    spike = {"on": True}
    orig_call = hook.__call__

    def spiking(attempt):
        if spike["on"]:
            time.sleep(0.3)

    sched.fault_hook = spiking
    view = sched.handle(DeviceDegrade(name=fleet[1].name, t_comm_scale=1.05))
    assert view.mode == "stale"
    assert view.events_behind == 1
    c = sched.metrics.counters
    assert c["deadline_missed"] == 1
    assert sched.health == HEALTH_DEGRADED
    assert sched.latest().mode == "stale"

    # Let the abandoned solve finish, then clean ticks recover.
    spike["on"] = False
    time.sleep(0.35)
    for _ in range(4):
        view = sched.handle(LoadTick(t_comm_jitter={}))
    assert view.events_behind == 0
    assert view.mode in ("warm", "cold")
    assert c["abandoned_solves_drained"] >= 1
    assert sched.health == HEALTH_HEALTHY
    sched.close()
    del orig_call


# -- chaos replay ----------------------------------------------------------


def _views_key(views):
    return [
        (v.result.k, tuple(v.result.w), tuple(v.result.n), v.result.obj_value)
        for v in views
    ]


def test_chaos_replay_empty_plan_matches_plain_replay(fleet, model):
    """Fault path disabled == fault path absent: an empty plan replay must
    serve placement-for-placement what the plain replay serves (the
    'zero-cost when disabled' half of the acceptance gate)."""
    trace = generate_trace("mixed", 14, seed=23, base_fleet=fleet)
    plain = replay(make_scheduler(fleet, model), trace)
    chaos = chaos_replay(make_scheduler(fleet, model), trace, FaultPlan())
    assert _views_key(chaos.views) == _views_key(plain.views)
    assert chaos.injected == {}
    assert chaos.ticks_to_healthy == 0
    assert chaos.violations(model.L) == []


def test_chaos_replay_same_seed_same_served_placements(fleet, model):
    """Same seed -> same injected schedule -> same served placements."""
    trace = generate_trace("drift", 12, seed=5, base_fleet=fleet)
    plan = FaultPlan(
        seed=3,
        faults=[
            FaultSpec(kind="solver_exception", p=0.25, start=1, end=12),
            FaultSpec(kind="nan_poison", at_ticks=[4]),
            FaultSpec(kind="malformed_event", at_ticks=[7]),
            FaultSpec(kind="dropout_burst", at_ticks=[6], rejoin_after=2),
        ],
    )
    r1 = chaos_replay(make_scheduler(fleet, model), trace, plan)
    r2 = chaos_replay(make_scheduler(fleet, model), trace, plan)
    assert r1.injected == r2.injected
    assert [(rec.source, rec.kind, rec.quarantined) for rec in r1.records] == [
        (rec.source, rec.kind, rec.quarantined) for rec in r2.records
    ]
    assert _views_key(r1.views) == _views_key(r2.views)
    assert r1.injected["injected_total"] >= 4
    assert r1.violations(model.L) == []
    assert r1.ticks_to_healthy is not None


def test_chaos_soak_contract_under_bundled_kinds(fleet, model):
    """Every fault kind at once: valid placement on every tick, poisoned
    events quarantined and accounted, health recovered — the same contract
    `make smoke-chaos` gates on the bundled trace/plan."""
    trace = generate_trace("mixed", 12, seed=23, base_fleet=fleet)
    plan = FaultPlan(
        seed=7,
        faults=[
            FaultSpec(kind="solver_exception", at_ticks=[2], transient=True),
            FaultSpec(kind="solver_exception", at_ticks=[5, 6]),
            FaultSpec(kind="latency_spike", at_ticks=[8], spike_s=0.01),
            FaultSpec(kind="nan_poison", at_ticks=[3, 9]),
            FaultSpec(kind="malformed_event", at_ticks=[4]),
            FaultSpec(kind="dropout_burst", at_ticks=[7], rejoin_after=2),
        ],
    )
    sched = make_scheduler(
        fleet, model, max_retries=1, retry_backoff_s=0.001,
        breaker_threshold=2, breaker_cooldown=1, healthy_after=2,
    )
    report = chaos_replay(sched, trace, plan)
    assert report.violations(model.L) == []
    c = sched.metrics.counters
    # 2 nan_poison + 1 malformed, plus possible collateral quarantines
    # (trace events naming a device the burst has out of the fleet); the
    # record-level reconciliation in violations() pins the exact split.
    assert c["events_quarantined"] >= 3
    assert c["fault_fired_solver_exception"] >= 3
    # The spike is always SCHEDULED; whether it fires depends on whether
    # its tick actually solved (a quarantined event or an open breaker
    # skips the solve — that skip is itself hardened behavior).
    assert report.injected["injected_latency_spike"] == 1
    assert report.injected["injected_dropout_burst"] == 1
    assert report.final_health == HEALTH_HEALTHY
    # The transient exception was saved by the retry ladder.
    assert c["solve_retry_success"] >= 1
    summary = report.summary()
    assert summary["quarantined"] == c["events_quarantined"]
    import json

    json.dumps(summary)  # plain types only

"""Platform-independent proof of the delta-upload (warm-tick wire) contract.

The packed single-dispatch path claims (backend_jax.StandardForm docstring):
a cold solve ships the drift-invariant static blob ONCE, and every
subsequent warm streaming tick ships only the few-KB dynamic blob — on a
tunneled TPU whose wire cost is per-operation, that contract IS the warm
tick's latency floor. BENCH captures can only measure it when the tunnel is
up; these tests pin it by construction, whatever the platform:

- transfer COUNT: exactly one static upload per distinct fleet shape, every
  drift tick a byte-identical static blob (content-addressed cache hit);
- transfer SIZE: the per-tick dynamic blob stays small in absolute terms
  and relative to the static blob, at dense M=16 and on the DeepSeek-V3
  E=256 / 32-device flagship (warm + duals layout, the largest dynamic
  blob the streaming path ever ships).

Reference contrast: /root/reference/src/distilp/solver/halda_p_solver.py
rebuilds and re-uploads the whole MILP every solve; the split is this
repo's design, so these assertions have no reference counterpart.
"""

from __future__ import annotations

import copy

import numpy as np

from distilp_tpu.common import load_from_profile_folder
from distilp_tpu.solver import StreamingReplanner, backend_jax
from distilp_tpu.utils import make_synthetic_fleet

GAP = 1e-3

# The "few KB" of the docstring, made exact: generous absolute ceilings so
# legitimate layout growth doesn't trip them, tight ratio so the static
# half always dominates (the contract is that warm ticks skip the BULK).
DYN_CEILING_DENSE = 32 * 1024  # bytes, M=16 dense warm tick
DYN_CEILING_MOE = 64 * 1024  # bytes, E=256 M=32 warm+duals tick
STATIC_OVER_DYN_MIN = 4.0


class _UploadSpy:
    """Wraps _static_to_device / _pack_dynamic, recording every transfer."""

    def __init__(self, monkeypatch):
        self.static_events: list[tuple[bytes, bool]] = []  # (blob bytes, uploaded)
        self.dyn_nbytes: list[int] = []
        orig_static = backend_jax._static_to_device
        orig_dyn = backend_jax._pack_dynamic

        def spy_static(vec):
            dev, uploaded = orig_static(vec)
            self.static_events.append((vec.tobytes(), uploaded))
            return dev, uploaded

        def spy_dyn(*args, **kwargs):
            blob = orig_dyn(*args, **kwargs)
            self.dyn_nbytes.append(blob.nbytes)
            return blob

        monkeypatch.setattr(backend_jax, "_static_to_device", spy_static)
        monkeypatch.setattr(backend_jax, "_pack_dynamic", spy_dyn)


def test_warm_tick_ships_only_dynamic_blob(monkeypatch):
    """Dense M=16 streaming: 1 static upload cold, 0 on drift ticks."""
    _, model = load_from_profile_folder("tests/profiles/llama_3_70b/online")
    devs = make_synthetic_fleet(16, seed=123)
    backend_jax.clear_static_cache()
    spy = _UploadSpy(monkeypatch)

    planner = StreamingReplanner(mip_gap=GAP, kv_bits="4bit", backend="jax")
    planner.step(devs, model)
    assert len(spy.static_events) == 1
    cold_blob, cold_uploaded = spy.static_events[0]
    assert cold_uploaded, "cold solve must upload the static blob"
    static_nbytes = len(cold_blob)
    # The static half is the BULK (A, c-structural, boxes, slack minima).
    assert static_nbytes > 10 * 1024, static_nbytes

    rng = np.random.default_rng(7)
    for _ in range(3):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
        tick = planner.step(devs, model)
        assert tick.certified

    # Drift-class perturbation leaves the static blob byte-identical, so
    # every warm tick is a content-addressed cache hit: ZERO static uploads.
    assert len(spy.static_events) == 4
    for blob, uploaded in spy.static_events[1:]:
        assert blob == cold_blob, "t_comm drift leaked into the static half"
        assert not uploaded, "warm tick re-uploaded the static blob"

    # The per-tick wire footprint is the dynamic blob alone, and it's small.
    assert len(spy.dyn_nbytes) == 4
    for nbytes in spy.dyn_nbytes:
        assert nbytes <= DYN_CEILING_DENSE, nbytes
        assert static_nbytes >= STATIC_OVER_DYN_MIN * nbytes, (
            static_nbytes, nbytes,
        )


def test_fleet_shape_change_is_a_cache_miss_not_a_wrong_solve(monkeypatch):
    """Shrinking the fleet changes the static blob SHAPE: a NEW upload, not
    a stale hit — cache misses degrade to cold-cost, never to a wrong
    answer. (M=8 matches test_streaming's layout so the jit cache is warm
    in a full-suite run.)"""
    _, model = load_from_profile_folder("tests/profiles/llama_3_70b/online")
    devs = make_synthetic_fleet(16, seed=123)
    backend_jax.clear_static_cache()
    spy = _UploadSpy(monkeypatch)

    planner = StreamingReplanner(mip_gap=GAP, kv_bits="4bit", backend="jax")
    planner.step(devs, model)
    small = planner.step(devs[:8], model)  # fleet shrinks mid-stream
    assert small.certified and len(small.w) == 8
    assert sum(small.w) * small.k == model.L

    assert len(spy.static_events) == 2
    (blob16, up16), (blob8, up8) = spy.static_events
    assert up16 and up8, "a new fleet shape must re-upload the static blob"
    assert len(blob8) != len(blob16)
    # ...and coming BACK to the original shape hits the bounded LRU cache.
    planner.step(devs, model)
    blob16b, up16b = spy.static_events[-1]
    assert blob16b == blob16 and not up16b


def test_moe_flagship_static_blob_drift_invariant():
    """E=256 / 32-device flagship, host-side: the packed static half is
    byte-identical under drift and the warm+duals dynamic blob is bounded.

    Runs NO solve (the flagship compile costs minutes); the contract lives
    entirely in the packing functions, so assembling the StandardForm twice
    is enough to pin it.
    """
    from distilp_tpu.profiler.api import profile_model
    from distilp_tpu.solver.api import _build_instance
    from distilp_tpu.solver.backend_jax import (
        _pack_dynamic,
        _pack_static,
        _rounding_arrays_np,
        build_standard_form,
    )

    split = profile_model(
        "tests/configs/deepseek_v3.json", batch_sizes=[1], sequence_length=128
    )
    model = split.to_model_profile()
    devs = make_synthetic_fleet(32, seed=11, pool_bytes=int(32e9))

    def build(fleet):
        Ks, _, coeffs, arrays = _build_instance(
            fleet, model, None, "8bit", None, None
        )
        feasible = [(k, model.L // k) for k in Ks if model.L // k >= len(fleet)]
        sf = build_standard_form(arrays, coeffs, feasible)
        return sf, coeffs, arrays, feasible

    sf, coeffs, arrays, feasible = build(devs)
    static0 = _pack_static(sf)

    M = len(devs)
    E = int(arrays.moe.E)
    n_k = len(sf.ks)
    # The largest dynamic blob the streaming path ships: warm incumbent +
    # stored root multipliers (the warm+duals layout of a real MoE tick).
    warm_tuple = (
        0,
        [model.L // sf.ks[0] // M] * M,
        [1] * M,
        [E // M] * M,
    )
    duals = (
        np.zeros(n_k), np.zeros(n_k), np.zeros((n_k, M)),
    )
    dyn0 = _pack_dynamic(
        sf, _rounding_arrays_np(coeffs, arrays.moe), GAP, warm_tuple, duals
    )
    assert dyn0.nbytes <= DYN_CEILING_MOE, dyn0.nbytes
    assert static0.nbytes >= STATIC_OVER_DYN_MIN * dyn0.nbytes, (
        static0.nbytes, dyn0.nbytes,
    )

    drifted = [copy.deepcopy(d) for d in devs]
    rng = np.random.default_rng(3)
    for d in drifted:
        d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
    sf2, coeffs2, arrays2, _ = build(drifted)
    static1 = _pack_static(sf2)
    assert np.array_equal(static0, static1), (
        "drift-class t_comm perturbation must not touch the static half"
    )
    # ...while the dynamic half DOES carry the drift (b rows move).
    dyn1 = _pack_dynamic(
        sf2, _rounding_arrays_np(coeffs2, arrays2.moe), GAP, warm_tuple, duals
    )
    assert dyn1.shape == dyn0.shape
    assert not np.array_equal(dyn0, dyn1)


def test_static_cache_lru_eviction_and_clear():
    """The content-addressed cache is bounded and clearable; eviction brings
    back the upload, never a stale array."""
    backend_jax.clear_static_cache()
    blobs = [np.full(8, float(i), np.float32) for i in range(
        backend_jax._STATIC_CACHE_CAP + 2)]
    for b in blobs:
        _, uploaded = backend_jax._static_to_device(b)
        assert uploaded
    # Most recent CAP entries hit...
    for b in blobs[-backend_jax._STATIC_CACHE_CAP:]:
        _, uploaded = backend_jax._static_to_device(b)
        assert not uploaded
    # ...the evicted ones re-upload.
    _, uploaded = backend_jax._static_to_device(blobs[0])
    assert uploaded
    backend_jax.clear_static_cache()
    _, uploaded = backend_jax._static_to_device(blobs[-1])
    assert uploaded
    backend_jax.clear_static_cache()

"""Live shard migration: warm hand-off reconciliation (ISSUE 19 tentpole).

The zero-downtime contract, pinned with real solvers on both LP engines
and with stub schedulers under concurrent ingest:

- every migrated shard's first post-move tick rides warm
  (``warm_resumes == shards moved``, ``cold_resumes == 0``);
- zero ``tick_cold`` in the whole moved phase (the bit-exact snapshot
  blob carries incumbents/duals/pool — nothing re-solves from scratch);
- per-fleet event cursors stay continuous through the move (no event is
  lost or double-applied while ticks are parked and replayed);
- a migration that fails mid-flip leaves routing on the intact source.

Solver-backed tests reuse the L=32 model + M=4 synthetic fleets of
tests/test_gateway.py so the jit programs are shared within the pytest
process.
"""

from __future__ import annotations

import threading

import pytest

from distilp_tpu.gateway import Gateway
from distilp_tpu.gateway.traces import make_fleet_from_spec
from distilp_tpu.sched import generate_trace

GAP = 1e-3
KS = [4, 8]


@pytest.fixture(scope="module")
def model():
    from distilp_tpu.profiler.api import profile_model

    return profile_model(
        "tests/configs/llama31_8b_4bit.json",
        batch_sizes=[1],
        sequence_length=128,
    ).to_model_profile()


def sched_kwargs(**extra):
    kw = dict(mip_gap=GAP, kv_bits="4bit", backend="jax", k_candidates=KS)
    kw.update(extra)
    return kw


def _stub_gateway(n_fleets: int, n_workers: int = 1, **gw_kwargs) -> Gateway:
    gw = Gateway(
        n_workers=n_workers,
        scheduler_factory="tests.procstub:make_scheduler",
        dynamic=True,
        **gw_kwargs,
    )
    for i in range(n_fleets):
        fid = f"m{i:02d}"
        gw.register_fleet(
            fid, make_fleet_from_spec(fid, {"m": 3, "seed": 500 + i}), "stub"
        )
    return gw


def _stub_events(gw: Gateway, fleets, n: int):
    for j in range(n):
        for fid in fleets:
            view = gw.handle_event(fid, f"ev{j}")
            assert view["kind"] == f"ev{j}"


# -- reconciliation with real solvers, both LP engines ---------------------


@pytest.mark.parametrize("engine", ["ipm", "pdhg"])
def test_live_migration_warm_reconciliation(model, engine):
    """Spawn a worker mid-trace and retire it again: every moved shard
    resumes warm, nothing cold-solves, and the per-fleet placements keep
    evolving from exactly where they left off."""
    extra = {"lp_backend": engine}
    if engine == "pdhg":
        extra["pdhg_iters"] = 400
    specs = {f"g{i}": {"m": 4, "seed": 90 + i} for i in range(2)}
    traces = {
        fid: generate_trace(
            "drift", 6, seed=95 + i,
            base_fleet=make_fleet_from_spec(fid, spec),
        )
        for i, (fid, spec) in enumerate(specs.items())
    }
    gw = Gateway(
        n_workers=1, scheduler_kwargs=sched_kwargs(**extra), dynamic=True
    )
    try:
        for fid, spec in specs.items():
            gw.register_fleet(fid, make_fleet_from_spec(fid, spec), model)
        # Warmup: cold solve + first warm tick per fleet, BEFORE the
        # baseline snapshot — migration must add zero cold work on top.
        for j in range(2):
            for fid in specs:
                gw.handle_event(fid, traces[fid][j])
        base = gw.metrics_snapshot()["shard_totals"]
        assert base["warm_resumes"] == 0

        widx, moved_out = gw.spawn_worker()
        assert gw.live_worker_ids() == [0, 1]
        # Consistent hashing moved SOME (not necessarily all) shards.
        assert 0 <= len(moved_out) <= len(specs)

        for j in range(2, 4):
            for fid in specs:
                view = gw.handle_event(fid, traces[fid][j])
                assert view.events_behind == 0

        _, moved_back = gw.retire_worker(widx)
        assert gw.live_worker_ids() == [0]
        assert len(moved_back) == len(moved_out)

        finals = {}
        for j in range(4, 6):
            for fid in specs:
                finals[fid] = gw.handle_event(fid, traces[fid][j])

        totals = gw.metrics_snapshot()["shard_totals"]
        counters = gw.metrics.snapshot()["counters"]
        migrated = counters.get("shards_migrated", 0)
        assert migrated == len(moved_out) + len(moved_back)
        # THE reconciliation: warm resumes == shards moved, zero cold.
        assert totals["warm_resumes"] - base["warm_resumes"] == migrated
        assert totals["cold_resumes"] == 0
        assert totals["tick_cold"] == base["tick_cold"]
        assert counters.get("migration_failed", 0) == 0
        # Cursor continuity: every fleet handled all 6 events, exactly.
        for fid in specs:
            assert gw._handled[fid] == 6
            assert finals[fid].result.k >= 1
    finally:
        gw.close()


def test_uninterrupted_and_migrated_runs_agree(model):
    """Same trace, one gateway static and one migrating mid-trace: final
    placements identical — a live move is invisible to the math."""
    spec = {"m": 4, "seed": 123}
    trace = generate_trace(
        "drift", 5, seed=321, base_fleet=make_fleet_from_spec("x0", spec)
    )

    def run(dynamic: bool):
        gw = Gateway(
            n_workers=1, scheduler_kwargs=sched_kwargs(), dynamic=dynamic
        )
        try:
            gw.register_fleet("x0", make_fleet_from_spec("x0", spec), model)
            out = None
            for j, ev in enumerate(trace):
                if dynamic and j == 3:
                    gw.spawn_worker()
                out = gw.handle_event("x0", ev)
            return out.result
        finally:
            gw.close()

    a, b = run(False), run(True)
    assert (a.k, a.w, a.n, a.obj_value) == (b.k, b.w, b.n, b.obj_value)


# -- stub-backed: concurrency, parking, failure recovery -------------------


def test_migration_parks_and_replays_concurrent_ingest():
    """Events ingested WHILE a shard is mid-flip park at the gate and
    replay on the destination in order: nothing lost, nothing doubled,
    per-fleet seq strictly continuous."""
    gw = _stub_gateway(n_fleets=4)
    try:
        fleets = sorted(gw._fleet_key)
        _stub_events(gw, fleets, 3)

        stop = threading.Event()
        errors = []
        seqs = {fid: 3 for fid in fleets}

        def ingest():
            j = 3
            while not stop.is_set():
                for fid in fleets:
                    try:
                        view = gw.handle_event(fid, f"ev{j}")
                    except Exception as e:  # pragma: no cover
                        errors.append(e)
                        return
                    # seq must be exactly prev+1: a lost parked event
                    # (or a double replay) breaks the chain instantly.
                    if view["seq"] != seqs[fid] + 1:
                        errors.append(
                            AssertionError(
                                f"{fid}: seq {view['seq']} after "
                                f"{seqs[fid]}"
                            )
                        )
                        return
                    seqs[fid] = view["seq"]
                j += 1

        t = threading.Thread(target=ingest)
        t.start()
        try:
            for _ in range(3):
                gw.spawn_worker()
                gw.retire_worker()
        finally:
            stop.set()
            t.join(timeout=30)
        assert not errors
        counters = gw.metrics.snapshot()["counters"]
        assert counters.get("shards_migrated", 0) > 0
        assert counters.get("migration_failed", 0) == 0
        # Post-churn: serving still works and the fleet is back to one.
        assert gw.live_worker_ids() == [0]
        for fid in fleets:
            view = gw.handle_event(fid, "tail")
            assert view["seq"] == seqs[fid] + 1
    finally:
        gw.close()


def test_migration_failure_leaves_source_intact():
    """A flip whose destination load blows up must recover: routing stays
    on the (still-serving) source, the failure is counted, and parked
    events replay against the source."""
    gw = _stub_gateway(n_fleets=2)
    try:
        fleets = sorted(gw._fleet_key)
        _stub_events(gw, fleets, 2)
        gw.spawn_worker()

        key = gw._fleet_key[fleets[0]]
        src_widx = gw._shards[key][2]
        dst_widx = next(w for w in gw.live_worker_ids() if w != src_widx)
        dst = gw.workers[dst_widx]

        real_load = dst.load_shard

        def broken_load(k, state):
            raise RuntimeError("injected: destination refuses the state")

        dst.load_shard = broken_load
        try:
            with pytest.raises(RuntimeError, match="injected"):
                gw.migrate_shard(fleets[0], dst_widx)
        finally:
            dst.load_shard = real_load

        counters = gw.metrics.snapshot()["counters"]
        assert counters.get("migration_failed", 0) == 1
        # Routing unchanged; the fleet still serves with continuous seq.
        assert gw._shards[key][2] == src_widx
        view = gw.handle_event(fleets[0], "after-failure")
        assert view["seq"] == 3
    finally:
        gw.close()


def test_static_gateway_refuses_dynamic_verbs():
    gw = Gateway(
        n_workers=1, scheduler_factory="tests.procstub:make_scheduler"
    )
    try:
        with pytest.raises(RuntimeError, match="dynamic"):
            gw.spawn_worker()
        with pytest.raises(RuntimeError, match="dynamic"):
            gw.retire_worker()
    finally:
        gw.close()


def test_retire_last_worker_refused():
    gw = _stub_gateway(n_fleets=1)
    try:
        with pytest.raises(RuntimeError, match="last worker"):
            gw.retire_worker()
    finally:
        gw.close()

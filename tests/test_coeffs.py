"""Unit tests for the coefficient model, incl. 1e-12 parity vs the reference."""

import sys
from pathlib import Path

import numpy as np
import pytest

from distilp_tpu.common import DeviceProfile, ModelProfile, load_from_profile_folder
from distilp_tpu.solver import (
    alpha_beta_xi,
    assign_sets,
    b_cio,
    b_prime,
    build_coeffs,
    kappa_constant,
    valid_factors_of_L,
)

REFERENCE_SRC = Path("/root/reference/src")

FIXTURES = [
    "hermes_70b",
    "llama_3_70b/4bit",
    "llama_3_70b/online",
    "qwen3_32b/bf16",
]


def test_valid_factors_of_L():
    assert valid_factors_of_L(80) == [1, 2, 4, 5, 8, 10, 16, 20, 40]
    assert valid_factors_of_L(64) == [1, 2, 4, 8, 16, 32]
    assert valid_factors_of_L(7) == [1]
    assert valid_factors_of_L(1) == []


def test_b_prime_hand_computed():
    model = ModelProfile(
        L=4, hk=2, ek=8, hv=2, ev=8, n_kv=10, b_layer=1000, Q="Q4_K"
    )
    # weights: 1.15 * 1000 = 1150
    # kv elems: 2*8*10 = 160 per side; 4-bit => 0.5 B/elem => 80 + 80 = 160
    # group scale: 1 + 2/64 = 1.03125 => 165.0
    assert b_prime(model, kv_bits_k=0.5) == int(1150 + 165.0)
    # 8-bit doubles the kv part
    assert b_prime(model, kv_bits_k=1.0) == int(1150 + 330.0)


def test_alpha_beta_xi_hand_computed():
    model = ModelProfile(
        L=4, hk=1, ek=1, hv=1, ev=1, n_kv=0, b_layer=0,
        f_q={"b_1": 100.0}, Q="F16",
    )
    dev = DeviceProfile(
        os_type="linux",
        scpu={"F16": {"b_1": 50.0}},
        T_cpu=1e9,
        t_kvcpy_cpu=0.5,
        t_kvcpy_gpu=0.7,
        has_cuda=True,
        sgpu_cuda={"F16": {"b_1": 200.0}},
        T_cuda=2e9,
        d_avail_cuda=1,
        t_ram2vram=0.1,
        t_vram2ram=0.2,
        is_unified_mem=False,
    )
    alpha, beta, xi = alpha_beta_xi(dev, model, kv_factor=1.0)
    # bprime = 0 here, so alpha = 100/50 + 0.5 = 2.5
    assert alpha == pytest.approx(2.5)
    # beta = (100/200 - 100/50) + (0.7 - 0.5) + 0 = -1.5 + 0.2
    assert beta == pytest.approx(-1.3)
    assert xi == pytest.approx(0.3)
    # unified memory zeroes xi
    dev_uma = dev.model_copy(update={"is_unified_mem": True})
    assert alpha_beta_xi(dev_uma, model, 1.0)[2] == 0.0


def test_b_cio_head_vs_tail():
    model = ModelProfile(L=1, b_in=1000, b_out=500, V=100)
    head = DeviceProfile(is_head=True, c_cpu=7)
    tail = DeviceProfile(is_head=False, c_cpu=7)
    assert b_cio(head, model) == pytest.approx(1000 / 100 + 500 + 7)
    assert b_cio(tail, model) == pytest.approx(7)


def test_assign_sets():
    devs = [
        DeviceProfile(os_type="mac_no_metal"),
        DeviceProfile(os_type="mac_metal"),
        DeviceProfile(os_type="linux"),
        DeviceProfile(os_type="android"),
        DeviceProfile(os_type="tpu"),
    ]
    sets = assign_sets(devs)
    assert sets == {"M1": [0], "M2": [1], "M3": [2, 3, 4]}


def test_build_coeffs_on_fixture(profiles_dir):
    devs, model = load_from_profile_folder(profiles_dir / "llama_3_70b" / "online")
    coeffs = build_coeffs(devs, model, kv_factor=0.5)
    assert coeffs.M == 2
    assert coeffs.set_id.tolist() == [2, 2]  # both mac_metal
    assert np.all(coeffs.a > 0)
    assert np.all(coeffs.metal_row)
    assert not np.any(coeffs.cuda_row)
    assert coeffs.t_comm.sum() == pytest.approx(0.06355 + 0.06292)
    # mac_metal devices: GPU delta should be negative (GPU faster than CPU)
    assert np.all(coeffs.b_gpu < 0)


@pytest.mark.skipif(not REFERENCE_SRC.exists(), reason="reference tree not present")
@pytest.mark.parametrize("fixture", FIXTURES)
@pytest.mark.parametrize("kv_factor", [0.5, 1.0, 2.0])
def test_coeff_parity_with_reference(profiles_dir, fixture, kv_factor):
    """Our vectorized coefficients match the reference scalar code to 1e-12."""
    if str(REFERENCE_SRC) not in sys.path:
        sys.path.insert(0, str(REFERENCE_SRC))
    ref_dc = pytest.importorskip("distilp.solver.components.dense_common")

    devs, model = load_from_profile_folder(profiles_dir / fixture)
    # Rebuild reference-typed profiles from the same JSON payloads.
    ref_devs = [
        ref_dc.DeviceProfile.model_validate(d.model_dump(mode="json")) for d in devs
    ]
    ref_model = ref_dc.ModelProfile.model_validate(model.model_dump(mode="json"))

    ref_sets = ref_dc.assign_sets(ref_devs)
    ref_a, ref_b, ref_c = ref_dc.objective_vectors(ref_devs, ref_model, ref_sets, kv_factor)
    ref_kappa = ref_dc.kappa_constant(ref_devs, ref_model, ref_sets)
    ref_bprime = ref_dc.b_prime(ref_model, kv_bits_k=kv_factor)

    sets = assign_sets(devs)
    assert sets == ref_sets
    coeffs = build_coeffs(devs, model, kv_factor, sets)

    assert coeffs.bprime == pytest.approx(ref_bprime, abs=1e-9)
    np.testing.assert_allclose(coeffs.a, ref_a, rtol=1e-12)
    np.testing.assert_allclose(coeffs.b_gpu, ref_b, rtol=1e-12)
    np.testing.assert_allclose(coeffs.xi, ref_c, rtol=1e-12)
    assert coeffs.kappa == pytest.approx(ref_kappa, rel=1e-12)
    for i, d in enumerate(devs):
        assert b_cio(d, model) == pytest.approx(
            ref_dc.b_cio_b(ref_devs[i], ref_model), rel=1e-12
        )
    assert kappa_constant(devs, model, sets) == pytest.approx(ref_kappa, rel=1e-12)

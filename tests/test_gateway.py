"""Gateway tier: routing, sharded workers, snapshot/restore, HTTP API.

Solver-backed tests reuse the L=32 model + M=4 synthetic fleets and the
[4, 8] k-grid of tests/test_sched.py, so the jit programs are shared
across modules within one pytest process and each tick after warmup is
milliseconds.
"""

from __future__ import annotations

import asyncio
import json
import threading

import pytest

from distilp_tpu.gateway import (
    ConsistentHashRouter,
    Gateway,
    GatewayHTTPServer,
    GatewaySnapshot,
    shard_key,
)
from distilp_tpu.gateway.traces import (
    is_gateway_trace,
    make_fleet_from_spec,
    read_gateway_trace,
    write_gateway_trace,
)
from distilp_tpu.sched import DeviceDegrade, LoadTick, generate_trace, write_trace
from distilp_tpu.sched.metrics import LatencyHist, SchedulerMetrics
from distilp_tpu.utils import make_synthetic_fleet

GAP = 1e-3
KS = [4, 8]


@pytest.fixture(scope="module")
def model():
    from distilp_tpu.profiler.api import profile_model

    return profile_model(
        "tests/configs/llama31_8b_4bit.json",
        batch_sizes=[1],
        sequence_length=128,
    ).to_model_profile()


def sched_kwargs(**extra):
    kw = dict(
        mip_gap=GAP, kv_bits="4bit", backend="jax", k_candidates=KS
    )
    kw.update(extra)
    return kw


def fleet_for(fleet_id: str, seed: int, m: int = 4):
    return make_fleet_from_spec(fleet_id, {"m": m, "seed": seed})


# -- router (no solver) ----------------------------------------------------


def test_router_deterministic_stable_and_balanced():
    keys = [shard_key(f"fleet-{i}") for i in range(200)]
    r1 = ConsistentHashRouter(4)
    r2 = ConsistentHashRouter(4)
    # Pure function of (key, worker count): two routers agree, across
    # processes too (SHA-1, not the salted builtin hash).
    assert r1.assignments(keys) == r2.assignments(keys)
    load = r1.load(keys)
    assert sum(load) == len(keys)
    # Virtual nodes keep the split from degenerating (no worker starved).
    assert min(load) >= len(keys) // 4 // 4

    # Reconfiguration churn ~1/N: going 4 -> 5 workers must not reshuffle
    # everything (warm state moves with a shard; churn is the cost).
    r5 = ConsistentHashRouter(5)
    moved = sum(1 for k in keys if r1.owner(k) != r5.owner(k))
    assert moved < len(keys) // 2


def test_shard_key_rejects_reserved_chars():
    with pytest.raises(ValueError):
        shard_key("a/b")
    with pytest.raises(ValueError):
        shard_key("")
    assert shard_key("f0", "m1") == "f0::m1"


# -- thread-safe metrics (satellite: two-thread hammer) --------------------


def test_metrics_hammer_two_threads_exact_counts():
    """Two writer threads hammer inc/observe while the main thread
    snapshots continuously. Locks make this exact: without them the
    counter misses increments (read-modify-write races) and the snapshot
    sort crashes on 'deque mutated during iteration'."""
    m = SchedulerMetrics()
    N = 20_000
    stop = threading.Event()

    def writer():
        for i in range(N):
            m.inc("hammered")
            m.observe("lat", float(i % 97))

    threads = [threading.Thread(target=writer) for _ in range(2)]
    snaps = []

    def reader():
        while not stop.is_set():
            snap = m.snapshot()
            snaps.append(snap)
            h = snap["latency"].get("lat")
            if h:
                # A torn hist would report count > 0 with mean 0/0 garbage;
                # under the lock every snapshot is internally consistent.
                assert h["count"] >= 1
                assert h["max_ms"] <= 96.0

    r = threading.Thread(target=reader)
    for t in threads:
        t.start()
    r.start()
    for t in threads:
        t.join()
    stop.set()
    r.join()
    assert m.counters["hammered"] == 2 * N
    final = m.snapshot()
    assert final["latency"]["lat"]["count"] == 2 * N
    assert len(snaps) >= 1
    assert json.dumps(final)


def test_latency_hist_concurrent_record_exact():
    h = LatencyHist()
    N = 50_000

    def rec():
        for i in range(N):
            h.record(float(i % 10))

    ts = [threading.Thread(target=rec) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert h.snapshot()["count"] == 2 * N


# -- multi-fleet traces (no solver) ----------------------------------------


def test_gateway_trace_roundtrip_and_detection(tmp_path):
    specs = {"fA": {"m": 3, "seed": 1}, "fB": {"m": 4, "seed": 2}}
    items = []
    for fid, spec in specs.items():
        devs = make_fleet_from_spec(fid, spec)
        for ev in generate_trace("drift", 3, seed=5, base_fleet=devs):
            items.append((fid, ev))
    path = tmp_path / "multi.jsonl"
    write_gateway_trace(path, specs, items)
    assert is_gateway_trace(path)
    back_specs, back_items = read_gateway_trace(path)
    assert back_specs == specs
    assert [(f, e.model_dump()) for f, e in back_items] == [
        (f, e.model_dump()) for f, e in items
    ]
    # Device names are namespaced per fleet — no aliasing across shards.
    assert all(
        d.name.startswith("fA-") for d in make_fleet_from_spec("fA", specs["fA"])
    )

    # A single-fleet trace is NOT detected as a gateway trace.
    single = tmp_path / "single.jsonl"
    write_trace(single, generate_trace(
        "drift", 3, seed=5, base_fleet=make_synthetic_fleet(3, seed=9)
    ))
    assert not is_gateway_trace(single)

    # Events for undeclared fleets are rejected, not silently dropped.
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"fleet": "ghost", "event": {"kind": "load"}}\n')
    with pytest.raises(ValueError, match="undeclared fleet"):
        read_gateway_trace(bad)


# -- the serving tier (JAX backend on CPU) ---------------------------------


def test_gateway_multi_fleet_concurrent_replay(model):
    """Three fleets through two workers, streams replayed concurrently:
    every tick certified, per-fleet ordering preserved (drift rides warm
    after the cold bootstrap), worker ownership fixed per shard."""
    specs = {f"g{i}": {"m": 4, "seed": 30 + i} for i in range(3)}
    gw = Gateway(n_workers=2, scheduler_kwargs=sched_kwargs())
    try:
        traces = {}
        for fid, spec in specs.items():
            devs = make_fleet_from_spec(fid, spec)
            gw.register_fleet(fid, devs, model)
            traces[fid] = generate_trace(
                "drift", 4, seed=40 + int(fid[1]), base_fleet=devs
            )

        async def drive(fid):
            out = []
            for ev in traces[fid]:
                out.append(await gw.handle_event_async(fid, ev))
            return out

        async def main():
            return await asyncio.gather(*(drive(f) for f in specs))

        views = asyncio.run(main())
        for fleet_views in views:
            assert all(v.result.certified for v in fleet_views)
            assert all(v.events_behind == 0 for v in fleet_views)
        snap = gw.metrics_snapshot()
        assert snap["shard_totals"]["events_total"] == 12
        assert snap["shard_totals"]["tick_failed"] == 0
        # Cold only for the bootstrap tick of each shard; drift rides warm.
        assert snap["shard_totals"]["tick_cold"] == 3
        assert snap["shard_totals"]["tick_warm"] == 9
        # Every event for a shard landed on its one owning worker.
        per_worker = [
            snap["counters"].get(f"worker_{i}_events", 0) for i in range(2)
        ]
        assert sum(per_worker) == 12
        assert gw.healthz()["status"] == "healthy"
    finally:
        gw.close()


def test_shard_health_isolation_broken_fleet_never_degrades_neighbor(model):
    """The per-shard HealthState pin: a fleet whose solves fail (injected
    via the scheduler's fault_hook seam) goes broken behind its breaker;
    a healthy fleet sharing the gateway — even the same worker — keeps
    serving certified warm ticks with untouched health."""
    gw = Gateway(
        n_workers=2,
        scheduler_kwargs=sched_kwargs(breaker_threshold=2, max_retries=0),
    )
    try:
        for fid, seed in (("sick", 50), ("well", 51)):
            gw.register_fleet(fid, fleet_for(fid, seed), model)
        # Bootstrap both (publish a placement so failures serve stale).
        for fid in ("sick", "well"):
            gw.handle_event(fid, LoadTick(t_comm_jitter={}))

        def explode(attempt):
            raise RuntimeError("injected: this shard's solver is down")

        gw.scheduler("sick").fault_hook = explode
        sick_dev = gw.scheduler("sick").fleet.device_list()[1].name
        well_dev = gw.scheduler("well").fleet.device_list()[1].name
        for i in range(4):
            v_sick = gw.handle_event(
                "sick", DeviceDegrade(name=sick_dev, t_comm_scale=1.01)
            )
            v_well = gw.handle_event(
                "well", DeviceDegrade(name=well_dev, t_comm_scale=1.01)
            )
            assert v_sick.events_behind > 0  # serving last-known-good
            assert v_well.events_behind == 0 and v_well.result.certified

        health = gw.healthz()
        assert health["shards"]["sick"]["state"] == "broken"
        assert health["shards"]["sick"]["breaker_open"] is True
        assert health["shards"]["well"]["state"] == "healthy"
        assert health["status"] == "broken"  # worst-of aggregation
        well_counters = gw.scheduler("well").metrics.counters
        assert well_counters["tick_failed"] == 0
        assert well_counters["drift_tick_warm"] == 4
    finally:
        gw.close()


@pytest.mark.parametrize("engine", ["ipm", "pdhg"])
def test_snapshot_restore_mid_trace_identical_and_warm(model, engine, tmp_path):
    """The acceptance pin, both LP engines: snapshot mid-trace, restore
    into a FRESH gateway (different worker count), replay the suffix —
    final placements identical to the uninterrupted run, first tick per
    restored shard warm (warm_resumes == shards), zero cold re-solves."""
    from distilp_tpu.gateway import load_snapshot, save_snapshot

    extra = {"lp_backend": engine}
    if engine == "pdhg":
        extra["pdhg_iters"] = 400
    specs = {f"s{i}": {"m": 4, "seed": 60 + i} for i in range(2)}
    traces = {
        fid: generate_trace(
            "drift", 4, seed=70 + i, base_fleet=make_fleet_from_spec(fid, spec)
        )
        for i, (fid, spec) in enumerate(specs.items())
    }
    items = [(fid, ev) for j in range(4) for fid, ev in
             ((f, traces[f][j]) for f in specs)]

    def fresh(n_workers):
        gw = Gateway(n_workers=n_workers, scheduler_kwargs=sched_kwargs(**extra))
        for fid, spec in specs.items():
            gw.register_fleet(fid, make_fleet_from_spec(fid, spec), model)
        return gw

    finals_a = {}
    gw_a = fresh(2)
    try:
        for fid, ev in items:
            finals_a[fid] = gw_a.handle_event(fid, ev)
    finally:
        gw_a.close()

    gw_b = fresh(2)
    try:
        for fid, ev in items[:4]:
            gw_b.handle_event(fid, ev)
        save_snapshot(gw_b.snapshot(), tmp_path)
    finally:
        gw_b.close()

    snap = load_snapshot(tmp_path)
    assert isinstance(snap, GatewaySnapshot)
    gw_c = Gateway(n_workers=3, scheduler_kwargs=sched_kwargs(**extra))
    try:
        gw_c.load_snapshot(snap)
        finals_c = {}
        uncovered = gw_c.uncovered(items)
        # The cursor covers exactly the snapshotted prefix.
        assert len(uncovered) == len(items) - 4
        for fid, ev in uncovered:
            finals_c[fid] = gw_c.handle_event(fid, ev)
        for fid in specs:
            a, c = finals_a[fid].result, finals_c[fid].result
            assert (a.k, a.w, a.n, a.obj_value) == (c.k, c.w, c.n, c.obj_value)
        totals = gw_c.metrics_snapshot()["shard_totals"]
        assert totals["warm_resumes"] == len(specs)
        assert totals["cold_resumes"] == 0
        assert totals["tick_cold"] == 0  # zero cold re-solves after restore
    finally:
        gw_c.close()


def test_snapshot_restore_preserves_latest_without_solving(model):
    """A restored gateway serves latest() immediately — the published
    placement rides the snapshot; no event needed before the first read."""
    gw = Gateway(n_workers=1, scheduler_kwargs=sched_kwargs())
    try:
        gw.register_fleet("p0", fleet_for("p0", 80), model)
        served = gw.handle_event("p0", LoadTick(t_comm_jitter={}))
        snap = gw.snapshot()
    finally:
        gw.close()
    # JSON round trip, like the on-disk file.
    snap = GatewaySnapshot.model_validate(json.loads(json.dumps(snap.model_dump())))
    gw2 = Gateway(n_workers=2, scheduler_kwargs=sched_kwargs())
    try:
        gw2.load_snapshot(snap)
        view = gw2.latest("p0")
        assert view.result.obj_value == served.result.obj_value
        assert view.events_behind == 0
    finally:
        gw2.close()


def test_http_api_roundtrip(model):
    """POST /events ticks the shard and returns the placement; GETs serve
    placement/health/metrics; unknown fleets 404. Exercised over a real
    socket against the asyncio server."""
    import urllib.error
    import urllib.request

    gw = Gateway(n_workers=2, scheduler_kwargs=sched_kwargs())

    def post(port, path, payload):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(payload).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def get(port, path):
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=60
            ) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        gw.register_fleet("h0", fleet_for("h0", 90), model)

        async def main():
            srv = GatewayHTTPServer(gw)
            await srv.start()
            loop = asyncio.get_running_loop()
            port = srv.port
            ev = {"kind": "load", "t_comm_jitter": {}}
            st, out = await loop.run_in_executor(
                None, post, port, "/events", {"fleet": "h0", "event": ev}
            )
            assert st == 200 and out["view"]["certified"]
            assert out["view"]["k"] in KS
            st, out = await loop.run_in_executor(
                None, get, port, "/placement/h0"
            )
            assert st == 200 and out["view"]["events_behind"] == 0
            st, out = await loop.run_in_executor(None, get, port, "/healthz")
            assert st == 200 and out["status"] == "healthy"
            st, out = await loop.run_in_executor(None, get, port, "/metrics")
            assert st == 200
            assert out["counters"]["gateway_events"] == 1
            assert out["shard_totals"]["tick_certified"] == 1
            st, _ = await loop.run_in_executor(
                None, get, port, "/placement/ghost"
            )
            assert st == 404
            st, _ = await loop.run_in_executor(None, get, port, "/nope")
            assert st == 404
            st, out = await loop.run_in_executor(
                None, post, port, "/events", {"fleet": "h0"}
            )
            await srv.close()
            return st

        st = asyncio.run(main())
        assert st == 400  # event-less POST is a client error
    finally:
        gw.close()


def test_structural_first_event_after_restore_is_not_a_cold_resume(model):
    """A structural event landing as the FIRST post-restore tick changes
    the shard's identity; the legitimate cold solve it triggers must count
    as resume_identity_changed — flagging it cold_resumes would fail the
    zero-downtime audit on a perfectly healthy restore."""
    from distilp_tpu.sched import DeviceLeave

    gw = Gateway(n_workers=1, scheduler_kwargs=sched_kwargs())
    try:
        gw.register_fleet("r0", fleet_for("r0", 97), model)
        gw.handle_event("r0", LoadTick(t_comm_jitter={}))
        snap = gw.snapshot()
    finally:
        gw.close()
    gw2 = Gateway(n_workers=1, scheduler_kwargs=sched_kwargs())
    try:
        gw2.load_snapshot(snap)
        victim = gw2.scheduler("r0").fleet.device_list()[-1].name
        view = gw2.handle_event("r0", DeviceLeave(name=victim))
        assert view.events_behind == 0
        c = gw2.scheduler("r0").metrics.counters
        assert c["resume_identity_changed"] == 1
        assert c["cold_resumes"] == 0 and c["warm_resumes"] == 0
    finally:
        gw2.close()


def test_register_duplicate_and_unknown_fleet_errors(model):
    gw = Gateway(n_workers=1, scheduler_kwargs=sched_kwargs())
    try:
        gw.register_fleet("d0", fleet_for("d0", 95), model)
        with pytest.raises(ValueError, match="already registered"):
            gw.register_fleet("d0", fleet_for("d0", 95), model)
        # Same fleet under a DIFFERENT model id must also be rejected: the
        # ingest directory is keyed by fleet, and a second shard would
        # silently clobber the first's routing and resume cursor.
        with pytest.raises(ValueError, match="already registered"):
            gw.register_fleet("d0", fleet_for("d0", 95), model, model_id="m2")
        with pytest.raises(KeyError, match="unknown fleet"):
            gw.handle_event("nope", LoadTick(t_comm_jitter={}))
    finally:
        gw.close()

"""Seeded fuzz: the JAX backend must match the HiGHS oracle on randomized
instances, dense and MoE.

The parity tests elsewhere pin specific fixtures; this file sweeps the
instance space — random fleet sizes/speeds/memories, perturbed model
scalars, random kv precision — so a formulation drift between the two
backends (a row the assembler adds that the rounding pricer does not
mirror, a bound the decomposition prices differently) surfaces as a
seeded, reproducible failure instead of a silent disagreement in the
field. Deterministic seeds: no flakes, failures replay exactly.
"""

from __future__ import annotations

import copy

import numpy as np
import pytest

pytest.importorskip("jax")
pytest.importorskip("scipy")

from distilp_tpu.common import load_model_profile  # noqa: E402
from distilp_tpu.profiler.api import profile_model  # noqa: E402
from distilp_tpu.solver import halda_solve  # noqa: E402
from distilp_tpu.utils import make_synthetic_fleet  # noqa: E402

GAP = 1e-3


def _perturb_fleet(devs, rng):
    """Random multiplicative noise on the load-bearing fleet coefficients."""
    for d in devs:
        d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.3, 3.0)))
        d.s_disk = max(1e6, d.s_disk * float(rng.uniform(0.3, 3.0)))
        d.d_avail_ram = max(int(1e9), int(d.d_avail_ram * rng.uniform(0.5, 2.0)))
        if d.d_avail_cuda is not None:
            d.d_avail_cuda = max(
                int(1e9), int(d.d_avail_cuda * rng.uniform(0.5, 2.0))
            )
        if d.d_avail_metal is not None:
            d.d_avail_metal = max(
                int(1e9), int(d.d_avail_metal * rng.uniform(0.5, 2.0))
            )
    return devs


def _agree(ref, got, gap=GAP):
    tol = 2 * gap * abs(ref.obj_value) + 1e-9
    assert abs(got.obj_value - ref.obj_value) <= tol, (
        f"backend disagreement: cpu={ref.obj_value} jax={got.obj_value} "
        f"(cpu k={ref.k}, jax k={got.k})"
    )


@pytest.mark.parametrize("seed", [11, 23, 37, 59, 71, 97])
def test_fuzz_dense_backends_agree(profiles_dir, seed):
    rng = np.random.default_rng(seed)
    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    M = int(rng.choice([3, 5, 8]))
    devs = _perturb_fleet(make_synthetic_fleet(M, seed=seed), rng)
    kv = str(rng.choice(["4bit", "8bit", "fp16"]))
    ref = halda_solve(devs, model, mip_gap=GAP, kv_bits=kv, backend="cpu")
    got = halda_solve(devs, model, mip_gap=GAP, kv_bits=kv, backend="jax")
    _agree(ref, got)
    assert sum(got.w) * got.k == model.L


@pytest.mark.parametrize("seed", [7, 41, 53])
def test_fuzz_moe_backends_agree(seed):
    rng = np.random.default_rng(seed)
    model = profile_model(
        "tests/configs/mixtral_8x7b.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()
    M = int(rng.choice([3, 4, 5]))
    devs = _perturb_fleet(
        make_synthetic_fleet(M, seed=seed, pool_bytes=int(96e9)), rng
    )
    # Random expert-load factors exercise the weighted-g path end to end.
    factors = [float(rng.uniform(0.2, 2.5)) for _ in range(M)]
    ref = halda_solve(
        devs, model, mip_gap=GAP, kv_bits="8bit", backend="cpu",
        load_factors=factors,
    )
    got = halda_solve(
        devs, model, mip_gap=GAP, kv_bits="8bit", backend="jax",
        load_factors=factors,
    )
    _agree(ref, got)
    assert sum(got.y) == model.n_routed_experts


@pytest.mark.parametrize("seed", [11, 29])
def test_fuzz_moe_extreme_load_skew_agrees(seed):
    """One device carrying ~90% of the realized expert load: the MoE g
    entries then dwarf the row's other coefficients (row scaling excludes
    g from the row magnitude, so scaled A entries land far above 1), and
    the f32 IPM must still agree with the f64 HiGHS oracle. Guards the
    conditioning regime the moderate-skew fuzz above never reaches."""
    rng = np.random.default_rng(seed)
    model = profile_model(
        "tests/configs/mixtral_8x7b.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()
    M = int(rng.choice([3, 4]))
    devs = _perturb_fleet(
        make_synthetic_fleet(M, seed=seed, pool_bytes=int(96e9)), rng
    )
    # ~90% of the load on one device, the rest sharing the remainder.
    hot = int(rng.integers(M))
    factors = [0.9 * M if i == hot else 0.1 * M / (M - 1) for i in range(M)]
    ref = halda_solve(
        devs, model, mip_gap=GAP, kv_bits="8bit", backend="cpu",
        load_factors=factors,
    )
    got = halda_solve(
        devs, model, mip_gap=GAP, kv_bits="8bit", backend="jax",
        load_factors=factors,
    )
    _agree(ref, got)
    assert sum(got.y) == model.n_routed_experts


def test_fuzz_streaming_drift_stays_certified(profiles_dir):
    """A long drift run: 8 warm ticks under compounding perturbation must
    stay certified and keep matching a cold solve at the end."""
    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    from distilp_tpu.solver import StreamingReplanner

    rng = np.random.default_rng(5)
    devs = make_synthetic_fleet(6, seed=5)
    planner = StreamingReplanner(mip_gap=GAP, kv_bits="4bit", backend="jax")
    planner.step(devs, model)
    for _ in range(8):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.8, 1.25)))
        tick = planner.step(devs, model)
        assert tick.certified
    cold = halda_solve(
        copy.deepcopy(devs), model, mip_gap=GAP, kv_bits="4bit", backend="jax"
    )
    _agree(cold, tick)


def test_gpt_oss_mxfp4_moe_solve_agrees():
    """GPT-OSS-20B (MXFP4, E=32, top-4): the third MoE family solves
    certified with both backends agreeing — MXFP4 quantization parsing and
    expert co-assignment compose."""
    model = profile_model(
        "tests/configs/gpt_oss_20b_mxfp4.json",
        batch_sizes=[1],
        sequence_length=128,
    ).to_model_profile()
    assert model.n_routed_experts == 32 and model.experts_per_token == 4
    devs = make_synthetic_fleet(4, seed=13, pool_bytes=int(8e9))
    ref = halda_solve(devs, model, mip_gap=GAP, kv_bits="8bit", backend="cpu")
    got = halda_solve(devs, model, mip_gap=GAP, kv_bits="8bit", backend="jax")
    _agree(ref, got)
    assert got.certified
    assert sum(got.y) == 32 and sum(got.w) * got.k == model.L


def test_qwen3_moe_a3b_solve_agrees():
    """Qwen3-30B-A3B (E=128, top-8): the fourth MoE family, wide expert
    count with small experts — stresses the y-repair scan budget."""
    model = profile_model(
        "tests/configs/qwen3_30b_a3b_8bit.json",
        batch_sizes=[1],
        sequence_length=128,
    ).to_model_profile()
    assert model.n_routed_experts == 128
    devs = make_synthetic_fleet(4, seed=17, pool_bytes=int(24e9))
    ref = halda_solve(devs, model, mip_gap=GAP, kv_bits="8bit", backend="cpu")
    got = halda_solve(devs, model, mip_gap=GAP, kv_bits="8bit", backend="jax")
    _agree(ref, got)
    assert got.certified
    assert sum(got.y) == 128 and sum(got.w) * got.k == model.L


@pytest.mark.parametrize("seed", [3, 19, 61, 83])
def test_fuzz_warm_matches_cold_after_drift(profiles_dir, seed):
    """Seeded warm-vs-cold parity: after random drift, a warm solve seeded
    from the PRE-drift result must land on the cold solve's objective within
    the certification band. Warm hints are re-priced exactly on-device, so
    a stale hint may slow pruning but must never bend the answer — this
    sweeps random drifts where test_streaming pins hand-picked ones."""
    rng = np.random.default_rng(seed)
    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    M = int(rng.choice([4, 6, 8]))
    devs = make_synthetic_fleet(M, seed=seed)
    kv = str(rng.choice(["4bit", "8bit"]))
    pre = halda_solve(devs, model, mip_gap=GAP, kv_bits=kv, backend="jax")
    assert pre.certified
    _perturb_fleet(devs, rng)  # heavy drift: 0.3-3x on t_comm/s_disk/mem
    cold = halda_solve(devs, model, mip_gap=GAP, kv_bits=kv, backend="jax")
    warm = halda_solve(
        devs, model, mip_gap=GAP, kv_bits=kv, backend="jax", warm=pre
    )
    assert cold.certified and warm.certified
    _agree(cold, warm)
    assert sum(warm.w) * warm.k == model.L


@pytest.mark.parametrize("seed", [29, 47])
def test_fuzz_warm_matches_cold_after_drift_moe(seed):
    """Same seeded warm-vs-cold parity on the MoE family, where the warm
    tick additionally re-evaluates the Lagrangian bound at the previous
    tick's persisted duals — stale duals must cost certification (handled
    by the caller's cold fallback), never a wrong certified objective."""
    rng = np.random.default_rng(seed)
    model = profile_model(
        "tests/configs/mixtral_8x7b.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()
    M = int(rng.choice([3, 4]))
    devs = make_synthetic_fleet(M, seed=seed, pool_bytes=int(96e9))
    pre = halda_solve(devs, model, mip_gap=GAP, kv_bits="8bit", backend="jax")
    assert pre.certified
    for d in devs:  # gentler drift: duals must stay warm-usable
        d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.7, 1.4)))
        d.s_disk = max(1e6, d.s_disk * float(rng.uniform(0.7, 1.4)))
    cold = halda_solve(devs, model, mip_gap=GAP, kv_bits="8bit", backend="jax")
    warm = halda_solve(
        devs, model, mip_gap=GAP, kv_bits="8bit", backend="jax", warm=pre
    )
    assert cold.certified
    if warm.certified:  # stale duals may miss the certificate; that is the
        _agree(cold, warm)  # documented fallback trigger, not a parity bug
    assert sum(warm.y) == model.n_routed_experts


@pytest.mark.parametrize("seed", [13, 67, 89])
def test_fuzz_per_k_winner_matches_default_sweep(profiles_dir, seed):
    """The per-k pruning regime must land on the same winner as the default
    global-incumbent sweep (both certified to the same gap), and every
    per-k entry must dominate the default sweep's reporting objective for
    that k (the reporting entry is only a best-found upper bound — a per-k
    certified optimum settling ABOVE it would be a bound bug). Seed 67
    regression-pins the k-fair compaction: global best-first spilled a
    crowded k's nodes and froze its certificate."""
    from distilp_tpu.common import kv_bits_to_factor
    from distilp_tpu.solver.api import halda_solve_per_k
    from distilp_tpu.solver.assemble import assemble
    from distilp_tpu.solver.backend_jax import solve_sweep_jax
    from distilp_tpu.solver.coeffs import (
        assign_sets,
        build_coeffs,
        valid_factors_of_L,
    )

    rng = np.random.default_rng(seed)
    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    M = int(rng.choice([4, 6]))
    devs = _perturb_fleet(make_synthetic_fleet(M, seed=seed), rng)
    default = halda_solve(devs, model, mip_gap=GAP, kv_bits="4bit", backend="jax")
    per_k = halda_solve_per_k(devs, model, mip_gap=GAP, kv_bits="4bit")
    assert per_k, "per-k sweep returned nothing on a feasible instance"
    winner = min(per_k, key=lambda r: r.obj_value)
    _agree(default, winner)
    for r in per_k:
        assert r.certified
        assert sum(r.w) * r.k == model.L

    # Dominance vs the default sweep's per-k reporting entries.
    coeffs = build_coeffs(
        devs, model, kv_bits_to_factor("4bit"), assign_sets(devs)
    )
    arrays = assemble(coeffs)
    kWs = [(k, model.L // k) for k in valid_factors_of_L(model.L)]
    reporting, _ = solve_sweep_jax(arrays, kWs, mip_gap=GAP, coeffs=coeffs)
    report_of = {r.k: r.obj_value for r in reporting if r is not None}
    for r in per_k:
        if r.k in report_of:
            tol = 2 * GAP * abs(report_of[r.k]) + 1e-9
            assert r.obj_value <= report_of[r.k] + tol, (
                f"k={r.k}: per-k optimum {r.obj_value} worse than the "
                f"default sweep's found incumbent {report_of[r.k]}"
            )


def test_fuzz_per_k_moe_matches_fixed_k_oracle():
    """Per-k mode composes with the MoE formulation (Lagrangian root
    seeding runs per k, y sums to E for every entry) and each certified
    entry matches the HiGHS oracle's fixed-k solve."""
    from distilp_tpu.solver.api import halda_solve_per_k

    rng = np.random.default_rng(31)
    model = profile_model(
        "tests/configs/mixtral_8x7b.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()
    M = int(rng.choice([3, 4]))
    devs = _perturb_fleet(
        make_synthetic_fleet(M, seed=31, pool_bytes=int(96e9)), rng
    )
    per_k = halda_solve_per_k(devs, model, mip_gap=GAP, kv_bits="8bit")
    assert per_k
    for r in per_k:
        assert r.certified
        assert sum(r.y) == model.n_routed_experts
        assert sum(r.w) * r.k == model.L
        oracle = halda_solve(
            devs, model, k_candidates=[r.k], mip_gap=GAP, kv_bits="8bit",
            backend="cpu",
        )
        _agree(oracle, r)

"""SLO engine: metrics timelines, burn-rate alerting, signals, history.

Everything here is backend-free on purpose: the timeline/SLO layer is
pure plumbing (stdlib + pydantic), and the gateway integration tests run
against fake shard schedulers injected through ``scheduler_factory`` —
so the whole file executes in milliseconds and the alerting semantics
are pinned deterministically, not statistically. The one real-scheduler
sample test lives in tests/test_obs.py next to the other solver-backed
obs integration tests (shared jit programs).
"""

from __future__ import annotations

import asyncio
import json
import time

import pytest

from distilp_tpu.gateway import Gateway, GatewayHTTPServer
from distilp_tpu.obs import (
    AlertRule,
    BurnWindow,
    FlightRecorder,
    SignalsPayload,
    SLOConfig,
    SLOEngine,
    SLOSpec,
    Timeline,
    TimelineSampler,
    Tracer,
    build_signals,
    synthesize_overload_timeline,
)
from distilp_tpu.sched.metrics import METRIC_REGISTRY, SchedulerMetrics

TRACES = "tests/traces"


# -- timeline semantics ------------------------------------------------------


def _ramp(tl: Timeline, name: str, pts):
    for t, v in pts:
        tl.record(name, t, v)


def test_timeline_record_window_bounds_capacity():
    tl = Timeline(capacity=4)
    _ramp(tl, "c.x", [(0, 0), (1, 1), (2, 2), (3, 3), (4, 4)])
    # Bounded ring: oldest fell off.
    assert tl.series("c.x") == [(1, 1), (2, 2), (3, 3), (4, 4)]
    assert tl.latest("c.x") == (4, 4)
    assert tl.bounds() == (1, 4)
    assert tl.window("c.x", 2.0, now=4) == [(2, 2), (3, 3), (4, 4)]
    assert tl.names() == ["c.x"]
    with pytest.raises(ValueError):
        Timeline(capacity=1)


def test_delta_uses_at_or_before_baseline():
    """Prometheus increase() semantics: a counter jump landing between a
    stale pre-window sample and the first in-window one is attributed to
    the window — a sampler delayed by the very overload it measures must
    not blind the alert to the burst it missed the edge of."""
    tl = Timeline()
    # Sample at t=0 (value 0), then a 6 s gap (sampler blocked), then the
    # post-jump plateau.
    _ramp(tl, "c.shed", [(0.0, 0.0), (6.0, 173.0), (6.1, 173.0), (6.2, 173.0)])
    # All in-window samples are post-jump; the baseline makes the delta.
    assert tl.delta("c.shed", 2.0, now=6.2) == 173.0
    # The rate spreads the jump over the MEASURED gap, never inflates.
    assert tl.rate("c.shed", 2.0, now=6.2) == pytest.approx(173.0 / 6.2)
    # No baseline and a single in-window point = insufficient data.
    tl2 = Timeline()
    tl2.record("c.y", 5.0, 10.0)
    assert tl2.delta("c.y", 2.0, now=5.0) is None
    assert tl2.rate("c.y", 2.0, now=5.0) is None
    # Two in-window points with no prior baseline: plain first-to-last.
    tl2.record("c.y", 6.0, 14.0)
    assert tl2.delta("c.y", 2.0, now=6.0) == 4.0


def test_ratio_idle_and_full_shed_semantics():
    tl = Timeline()
    _ramp(tl, "c.bad", [(0, 0), (1, 8), (2, 8), (3, 8)])
    _ramp(tl, "c.total", [(0, 0), (1, 10), (2, 10), (3, 10)])
    # Burst window: 8 bad of 10 offered.
    assert tl.ratio("c.bad", "c.total", 1.5, now=1.0) == pytest.approx(0.8)
    # Idle window (deltas both zero): request-weighted budget burns 0 —
    # this is what lets a flood's alert clear once the burst slides out.
    assert tl.ratio("c.bad", "c.total", 1.5, now=3.0) == 0.0
    # Degenerate: bad moved, total did not -> clamp to 1, not div-zero.
    tl2 = Timeline()
    _ramp(tl2, "c.bad", [(0, 0), (1, 5)])
    _ramp(tl2, "c.total", [(0, 0), (1, 0)])
    assert tl2.ratio("c.bad", "c.total", 2.0, now=1.0) == 1.0
    # Unknown series: insufficient data, never zero.
    assert tl2.ratio("c.bad", "c.nope", 2.0, now=1.0) is None


def test_frac_above_and_trend():
    tl = Timeline()
    _ramp(tl, "g.p99", [(0, 100), (1, 600), (2, 700), (3, 100)])
    assert tl.frac_above("g.p99", 500.0, 4.0, now=3.0) == pytest.approx(0.5)
    assert tl.frac_above("g.p99", 500.0, 0.5, now=3.0) == 0.0
    assert tl.frac_above("g.none", 500.0, 4.0, now=3.0) is None
    _ramp(tl, "g.depth", [(0, 0), (1, 2), (2, 4), (3, 6)])
    assert tl.trend_per_s("g.depth", 4.0, now=3.0) == pytest.approx(2.0)
    assert tl.trend_per_s("g.depth", 0.1, now=3.0) is None


def test_dump_load_byte_and_replay_identical(tmp_path):
    tl = synthesize_overload_timeline(duration_s=10.0, period_s=0.5)
    path = tl.dump(tmp_path / "t.jsonl")
    tl2 = Timeline.load(path)
    # Byte-stable re-dump AND identical evaluation (full float precision
    # survives the JSON round trip, so window membership cannot shift).
    assert tl2.to_jsonl() == tl.to_jsonl()
    cfg = SLOConfig.from_json(f"{TRACES}/slo_overload_spec.json")
    assert SLOEngine(cfg, tl2).replay(0.5) == SLOEngine(cfg, tl).replay(0.5)
    with pytest.raises(ValueError):
        Timeline.from_jsonl("")
    with pytest.raises(ValueError):
        Timeline.from_jsonl('{"not": "a header"}\n')


def test_committed_fixture_regenerates_byte_exact():
    """The committed synthetic overload timeline is a pure function of
    its recipe (no clocks, no RNG) — regeneration must be byte-exact,
    same contract as the committed traffic captures."""
    committed = open(f"{TRACES}/slo_timeline_overload.jsonl").read()
    assert synthesize_overload_timeline().to_jsonl() == committed


def test_committed_expected_alert_sequence_matches_replay():
    """The smoke-slo offline pin, asserted in-process: replaying the
    committed timeline against the committed spec reproduces the
    committed expected sequence exactly (tier, state, firing bucket)."""
    tl = Timeline.load(f"{TRACES}/slo_timeline_overload.jsonl")
    cfg = SLOConfig.from_json(f"{TRACES}/slo_overload_spec.json")
    events = SLOEngine(cfg, tl).replay(step_s=0.1)
    expect = json.loads(open(f"{TRACES}/slo_expected_alerts.json").read())
    t0 = tl.bounds()[0]
    got = [
        {
            "slo": e["slo"], "severity": e["severity"], "state": e["state"],
            "bucket": int((e["t"] - t0) / expect["bucket_s"]),
        }
        for e in events
    ]
    assert got == expect["events"]
    # The sequence is the full incident story: every open has its close.
    opens = [(e["slo"], e["severity"]) for e in events if e["state"] == "open"]
    closes = [
        (e["slo"], e["severity"]) for e in events if e["state"] == "close"
    ]
    assert sorted(opens) == sorted(closes)
    # And a second replay is identical (pure function).
    assert SLOEngine(cfg, tl).replay(step_s=0.1) == events


# -- spec validation ---------------------------------------------------------


def test_spec_kind_field_validation():
    with pytest.raises(ValueError):
        SLOSpec(name="x", kind="ratio", objective=0.99)  # missing series
    with pytest.raises(ValueError):
        SLOSpec(name="x", kind="threshold", objective=0.99, series="s")
    with pytest.raises(ValueError):
        SLOSpec(
            name="x", kind="ratio", objective=1.5,
            bad_series="b", total_series="t",
        )
    spec = SLOSpec(
        name="x", kind="ratio", objective=0.999,
        bad_series="b", total_series="t",
    )
    assert spec.budget == pytest.approx(0.001)
    # Default ladder is the SRE recipe: page 14.4x (1h AND 5m), warn 6x.
    sev = {r.severity: r for r in spec.alerts}
    assert {w.window_s for w in sev["page"].windows} == {3600, 300}
    assert all(w.burn_rate == 14.4 for w in sev["page"].windows)
    assert all(w.burn_rate == 6.0 for w in sev["warn"].windows)


# -- the alert state machine -------------------------------------------------


def _one_slo(windows, clear_factor=0.9, clear_hold_s=1.0, objective=0.99):
    return SLOConfig(
        slos=[
            SLOSpec(
                name="avail", kind="ratio", objective=objective,
                bad_series="c.bad", total_series="c.total",
                alerts=[
                    AlertRule(
                        severity="page",
                        windows=[
                            BurnWindow(window_s=w, burn_rate=b)
                            for w, b in windows
                        ],
                        clear_factor=clear_factor,
                        clear_hold_s=clear_hold_s,
                    )
                ],
            )
        ]
    )


def test_multi_window_and_gate():
    """A short spike trips the short window but not the long one: the
    rule must NOT fire until both burn at once (the reason multi-window
    alerting exists — a long-resolved blip cannot page)."""
    tl = Timeline()
    # 10/s offered throughout; bad only in [5.0, 5.4) — a 0.4 s blip.
    for i in range(101):
        t = i * 0.1
        bad = 4.0 if t >= 5.4 else (max(0.0, (t - 5.0)) * 10 if t >= 5.0 else 0.0)
        tl.record_many(t, {"c.total": 10.0 * t, "c.bad": bad})
    cfg = _one_slo([(8.0, 30.0), (0.5, 30.0)])
    engine = SLOEngine(cfg, tl)
    events = engine.replay(step_s=0.1)
    # Short window burns during the blip (ratio ~0.4 -> burn ~40 >= 30).
    assert tl.ratio("c.bad", "c.total", 0.5, now=5.3) > 0.3
    # Long window never gets past 30x0.01: 4 bad / 80 offered = 0.05 -> 5.
    assert events == []


def test_hysteresis_no_flapping():
    """Burn oscillating just under/over the threshold flaps the raw
    signal every step; the alert must open once and close once."""
    tl = Timeline()
    # Error ratio alternates 0.2 / 0.12 per step between t=10 and t=20,
    # zero outside: burn (budget 0.01, threshold 15) flaps 20 <-> 12 —
    # above, then BELOW threshold but above clear_factor*threshold=13.5?
    # 12 < 13.5, so each dip starts the clear hold; the 2 s hold outlasts
    # every dip (0.5 s), so the alert stays open until the burst truly
    # ends.
    total = bad = 0.0
    for i in range(301):
        t = i * 0.1
        total += 1.0
        if 10.0 <= t < 20.0:
            step = int(t * 2) % 2  # flips every 0.5 s
            bad += 0.2 if step == 0 else 0.12
        tl.record_many(t, {"c.total": total, "c.bad": bad})
    cfg = _one_slo([(2.0, 15.0), (0.5, 15.0)], clear_hold_s=2.0)
    events = SLOEngine(cfg, tl).replay(step_s=0.1)
    kinds = [e["state"] for e in events]
    assert kinds == ["open", "close"], events
    assert 10.0 <= events[0]["t"] <= 13.0  # opens early in the burst
    assert events[1]["t"] >= 20.0  # held open across every dip


def test_insufficient_data_holds_state():
    """A sampler gap (no samples at all) must neither fire nor clear a
    burning alert: None is 'unknown', not 'zero'."""
    tl = Timeline()
    total = bad = 0.0
    for i in range(51):  # burn hard for 5 s
        t = i * 0.1
        total += 1.0
        bad += 0.5
        tl.record_many(t, {"c.total": total, "c.bad": bad})
    cfg = _one_slo([(2.0, 10.0), (0.5, 10.0)], clear_hold_s=0.0)
    engine = SLOEngine(cfg, tl)
    assert [e["state"] for e in engine.evaluate(now=5.0)] == ["open"]
    # Evaluate far past the data: every window is empty -> ratio None ->
    # the alert HOLDS (a dead sampler cannot silently close an incident).
    assert engine.evaluate(now=100.0) == []
    assert engine.firing()


def test_transitions_hit_counters_flight_and_spans():
    tl = synthesize_overload_timeline(duration_s=40.0, period_s=0.2)
    cfg = SLOConfig.from_json(f"{TRACES}/slo_live_spec.json")
    metrics = SchedulerMetrics()
    flight = FlightRecorder(capacity=64)
    tracer = Tracer(capacity=256)
    engine = SLOEngine(
        cfg, tl, metrics=metrics, tracer=tracer, flight=flight
    )
    events = engine.replay(step_s=0.2)
    opened = sum(1 for e in events if e["state"] == "open")
    closed = sum(1 for e in events if e["state"] == "close")
    assert opened >= 1 and closed >= 1
    counters = metrics.snapshot()["counters"]
    assert counters["slo_alert_opened"] == opened
    assert counters["slo_alert_closed"] == closed
    # First-class flight records on the slo ring, one per transition.
    recs = [r for r in flight.snapshot("slo") if r.get("kind") == "slo_alert"]
    assert len(recs) == len(events)
    assert recs[0]["state"] == "open" and recs[0]["slo"] == "availability"
    # sched.alert span events, zero-duration, attrs carry the identity.
    alert_spans = [s for s in tracer.spans() if s["name"] == "sched.alert"]
    assert len(alert_spans) == len(events)
    assert alert_spans[0]["attrs"]["severity"] == "page"
    assert alert_spans[0]["dur_ms"] == 0.0
    # Registry coverage for the two counters (DLP019's other half).
    assert "slo_alert_opened" in METRIC_REGISTRY
    assert "slo_alert_closed" in METRIC_REGISTRY


# -- signals -----------------------------------------------------------------


def test_build_signals_schema_trend_and_headroom():
    tl = Timeline()
    for i in range(61):
        t = i * 1.0
        tl.record_many(
            t,
            {
                "queue_depth.w0": 0.1 * i,  # rising: trend > 0
                "queue_depth.w1": 0.0,
                "c.gateway_events": 10.0 * i,
                "c.events_shed": 0.0,
            },
        )
    cfg = _one_slo([(10.0, 10.0)])
    engine = SLOEngine(cfg, tl)
    sig = build_signals(tl, engine=engine, capacity_eps=25.0, now=60.0)
    # Round-trips through its own schema (the federation contract).
    assert SignalsPayload.model_validate(sig.model_dump()).version == 1
    assert [w.worker for w in sig.workers] == [0, 1]
    assert sig.workers[0].queue_depth_trend_per_s == pytest.approx(0.1)
    assert sig.workers[1].queue_depth_trend_per_s == pytest.approx(0.0)
    assert sig.queue_depth_total == pytest.approx(6.0)
    assert sig.recent_eps == pytest.approx(10.0)
    assert sig.headroom_eps == pytest.approx(15.0)
    assert sig.slos[0].slo == "avail" and sig.slos[0].firing == []
    # Burn keys exist per configured window.
    assert set(sig.slos[0].burn) == {"10s"}
    # No memory ledger live: the memory field is honestly absent (None),
    # and the schema still validates (the additive-field contract).
    assert sig.mem_headroom_bytes is None


def test_build_signals_mem_headroom_rides_a_live_memory_ledger():
    """PR 15's additive /signals field: with a memory ledger enabled the
    payload carries mem_headroom_bytes = budget - RSS, schema-validated
    at version 1 (old consumers unaffected, the federation tier gets the
    scale-up-has-memory signal for free)."""
    from distilp_tpu.obs import memory as obs_memory

    tl = Timeline()
    tl.record_many(0.0, {"c.gateway_events": 0.0})
    tl.record_many(30.0, {"c.gateway_events": 300.0})
    led = obs_memory.enable(
        obs_memory.MemoryLedger(budget_bytes=1 << 40)
    )
    try:
        sig = build_signals(tl, capacity_eps=25.0, now=30.0)
        payload = SignalsPayload.model_validate(sig.model_dump())
        assert payload.version == 1
        rss = obs_memory.read_proc_status()["rss_bytes"]
        if rss is None:
            assert payload.mem_headroom_bytes is None
        else:
            assert payload.mem_headroom_bytes is not None
            assert 0 < payload.mem_headroom_bytes < float(1 << 40)
            assert payload.mem_headroom_bytes == pytest.approx(
                led.headroom_bytes(), rel=0.05
            )
    finally:
        obs_memory.disable()


# -- the sampler -------------------------------------------------------------


def test_sampler_counts_samples_and_errors_and_survives_failures():
    tl = Timeline()
    metrics = SchedulerMetrics()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("probe hit a stopping worker")
        return {"c.x": float(calls["n"])}

    s = TimelineSampler(tl, flaky, period_s=0.001, metrics=metrics)
    assert s.sample_once(now=1.0) is True
    assert s.sample_once(now=2.0) is False  # counted, not fatal
    assert s.sample_once(now=3.0) is True
    counters = metrics.snapshot()["counters"]
    assert counters["timeline_samples"] == 2
    assert counters["timeline_sample_error"] == 1
    assert [v for _, v in tl.series("c.x")] == [1.0, 3.0]
    # on_sample failures are counted too (the engine must not kill the
    # sampler thread).
    s2 = TimelineSampler(
        tl, lambda: {"c.y": 1.0}, period_s=0.001, metrics=metrics,
        on_sample=lambda _tl, _now: (_ for _ in ()).throw(ValueError("x")),
    )
    assert s2.sample_once(now=1.0) is False
    assert metrics.snapshot()["counters"]["timeline_sample_error"] == 2


def test_sampler_thread_start_stop_idempotent():
    tl = Timeline()
    s = TimelineSampler(tl, lambda: {"c.x": 1.0}, period_s=0.005)
    s.start()
    s.start()  # second start is a no-op
    deadline = time.monotonic() + 2.0
    while s.samples < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert s.samples >= 3
    s.stop()
    assert not s.running
    n = s.samples
    s.stop()  # idempotent
    time.sleep(0.05)
    assert s.samples == n  # truly stopped


# -- gateway integration (fake shard schedulers: no solver, no jax) ----------


class _FakeSched:
    """The minimal Scheduler face the gateway needs (tests inject it
    through scheduler_factory, like test_gateway's failing schedulers)."""

    def __init__(self):
        self.metrics = SchedulerMetrics()
        self.health = "healthy"

    def handle(self, event):
        self.metrics.inc("events_total")
        return None

    def latest(self):
        return None

    def health_snapshot(self):
        return {"state": self.health}

    def metrics_snapshot(self):
        return self.metrics.snapshot()

    def close(self):
        pass


def _fake_gateway(n_workers=2, **kw):
    return Gateway(
        n_workers=n_workers,
        scheduler_factory=lambda devices, model: _FakeSched(),
        **kw,
    )


def test_gateway_timeline_sample_series_conventions():
    gw = _fake_gateway()
    try:
        gw.register_fleet("f0", [], None)
        gw.handle_event("f0", object())
        sample = gw.timeline_sample()
        # Counters, shard totals, queue depths, and the derived offered
        # series all follow the documented naming.
        assert sample["c.gateway_events"] == 1.0
        assert sample["c.events_shed"] == 0.0  # zero-valued, ALWAYS present
        assert sample["c.events_offered"] == 1.0
        assert sample["shards.events_total"] == 1.0
        assert sample["queue_depth.w0"] == 0.0
        assert sample["queue_depth.w1"] == 0.0
        assert sample["queue_depth.max"] == 0.0
        assert "lat.gateway_event_to_placement.p99_ms" in sample
    finally:
        gw.close()


def test_gateway_close_stops_attached_samplers_idempotently():
    gw = _fake_gateway()
    tl = Timeline()
    sampler = gw.attach_sampler(
        TimelineSampler(
            tl, gw.timeline_sample, period_s=0.005, metrics=gw.metrics
        )
    )
    sampler.start()
    deadline = time.monotonic() + 2.0
    while sampler.samples < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert sampler.samples >= 2
    gw.close()
    assert not sampler.running
    counters = gw.metrics.snapshot()["counters"]
    # Every tick before the stop landed cleanly; none raced the teardown.
    assert counters.get("timeline_sample_error", 0) == 0
    gw.close()  # idempotent, samplers already stopped


def test_gateway_close_during_prom_scrape_counts_no_errors():
    """The PR 8 bench gotcha, pinned at the source: a prom-scrape thread
    attached to the gateway is stopped by close() BEFORE the workers, so
    a clean shutdown can never count prom_scrape_error."""
    from distilp_tpu.gateway.loadgen import PromScraper

    for _ in range(3):  # a few rounds to give the race a chance
        gw = _fake_gateway()
        gw.register_fleet("f0", [], None)
        scraper = PromScraper(gw, period_s=0.001).start()
        deadline = time.monotonic() + 2.0
        while scraper.scrapes < 3 and time.monotonic() < deadline:
            time.sleep(0.002)
        assert scraper.scrapes >= 3  # it really was scraping
        gw.close()  # no explicit scraper.stop(): close owns the ordering
        counters = gw.metrics.snapshot()["counters"]
        assert counters.get("prom_scrape_error", 0) == 0
        scraper.stop()  # harness double-stop stays safe


def test_no_slo_knobs_means_no_slo_counters():
    """Byte-identical pin: serving without any timeline/SLO knob mints
    ZERO slo/timeline counters and no sampler exists — the untouched
    path is the pre-SLO path (same contract as the spec-off pin)."""
    gw = _fake_gateway()
    try:
        gw.register_fleet("f0", [], None)
        for _ in range(5):
            gw.handle_event("f0", object())
        counters = gw.metrics.snapshot()["counters"]
        assert not any(
            k.startswith(("timeline_", "slo_")) for k in counters
        ), counters
        assert gw.timeline is None and gw.slo_engine is None
        assert gw._samplers == []
    finally:
        gw.close()


def test_http_slo_and_signals_routes():
    gw = _fake_gateway()
    tl = Timeline()
    cfg = _one_slo([(10.0, 10.0)])
    engine = SLOEngine(cfg, tl, metrics=gw.metrics)
    sampler = gw.attach_sampler(
        TimelineSampler(
            tl, gw.timeline_sample, period_s=0.01, metrics=gw.metrics,
            on_sample=lambda _tl, now: engine.evaluate(now),
        )
    )
    gw.attach_slo(engine, tl, capacity_eps=100.0)
    sampler.start()

    import urllib.error
    import urllib.request

    def get(port, path):
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30
            ) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        gw.register_fleet("f0", [], None)
        deadline = time.monotonic() + 2.0
        while sampler.samples < 3 and time.monotonic() < deadline:
            time.sleep(0.01)

        async def main():
            srv = GatewayHTTPServer(gw)
            await srv.start()
            loop = asyncio.get_running_loop()
            st, slo = await loop.run_in_executor(
                None, get, srv.port, "/slo"
            )
            assert st == 200
            assert slo["slos"][0]["name"] == "avail"
            assert slo["alerts_open"] == 0
            st, sig = await loop.run_in_executor(
                None, get, srv.port, "/signals"
            )
            assert st == 200
            payload = SignalsPayload.model_validate(sig)
            assert payload.max_sustainable_eps == 100.0
            assert [w.worker for w in payload.workers] == [0, 1]
            await srv.close()

        asyncio.run(main())
    finally:
        gw.close()


def test_http_slo_404_when_not_enabled():
    gw = _fake_gateway()

    import urllib.error
    import urllib.request

    def get(port, path):
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30
            ) as r:
                return r.status
        except urllib.error.HTTPError as e:
            return e.code

    try:
        async def main():
            srv = GatewayHTTPServer(gw)
            await srv.start()
            loop = asyncio.get_running_loop()
            assert await loop.run_in_executor(
                None, get, srv.port, "/slo"
            ) == 404
            assert await loop.run_in_executor(
                None, get, srv.port, "/signals"
            ) == 404
            await srv.close()

        asyncio.run(main())
    finally:
        gw.close()


# -- solver slo CLI ----------------------------------------------------------


def test_slo_cli_offline_check_ok_and_expect_mismatch(tmp_path):
    from distilp_tpu.cli.solver_cli import main as cli_main

    ok = cli_main(
        [
            "slo",
            "--spec", f"{TRACES}/slo_overload_spec.json",
            "--timeline", f"{TRACES}/slo_timeline_overload.jsonl",
            "--step-s", "0.1",
            "--expect", f"{TRACES}/slo_expected_alerts.json",
            "--check", "--quiet",
        ]
    )
    assert ok == 0
    # Tamper with the expectation: exact-sequence mismatch must fail.
    expect = json.loads(open(f"{TRACES}/slo_expected_alerts.json").read())
    expect["events"][0]["bucket"] += 1
    tampered = tmp_path / "expect.json"
    tampered.write_text(json.dumps(expect))
    rc = cli_main(
        [
            "slo",
            "--spec", f"{TRACES}/slo_overload_spec.json",
            "--timeline", f"{TRACES}/slo_timeline_overload.jsonl",
            "--step-s", "0.1",
            "--expect", str(tampered),
            "--check", "--quiet",
        ]
    )
    assert rc == 1
    # Nothing to evaluate / missing spec are usage errors.
    assert cli_main(["slo", "--check"]) == 2
    assert cli_main(["slo", "--timeline", "x.jsonl"]) == 2


def test_slo_cli_history_trend_check(tmp_path):
    from distilp_tpu.cli.solver_cli import main as cli_main

    hist = tmp_path / "BENCH_HISTORY.jsonl"
    rows = [
        {"round": 1, "value": 30.0, "warm_tick_ms": 16.0, "spec_hit_rate": 0.93},
        {"round": 2, "value": 31.0, "warm_tick_ms": 16.5, "spec_hit_rate": 0.92},
        {"round": 3, "value": 30.5, "warm_tick_ms": 16.2, "spec_hit_rate": 0.94},
    ]
    hist.write_text("".join(json.dumps(r) + "\n" for r in rows))
    assert cli_main(
        ["slo", "--history", str(hist), "--check", "--quiet"]
    ) == 0
    # Regress the newest round's warm tick 2x: the trend rule fires.
    rows.append({"round": 4, "value": 30.2, "warm_tick_ms": 40.0,
                 "spec_hit_rate": 0.93})
    hist.write_text("".join(json.dumps(r) + "\n" for r in rows))
    assert cli_main(
        ["slo", "--history", str(hist), "--check", "--quiet"]
    ) == 1


def test_evaluate_history_table_and_tolerances():
    from distilp_tpu.obs.slo import evaluate_history

    rows = [
        {"value": 30.0, "spec_hit_rate": 0.9},
        {"value": 32.0, "spec_hit_rate": 0.9},
        {"value": 31.0, "spec_hit_rate": 0.5},  # hit rate collapsed
    ]
    table, violations = evaluate_history(rows)
    assert any(v.startswith("spec_hit_rate") for v in violations)
    assert not any(v.startswith("value") for v in violations)
    by_key = {r["key"]: r for r in table}
    assert by_key["value"]["latest"] == 31.0
    # One known-key row exists even with zero data.
    assert by_key["warm_tick_ms"]["median"] is None


def test_bench_history_append_load_roundtrip(tmp_path):
    from tools.bench_history import (
        HISTORY_KEYS,
        append_history,
        load_history,
    )

    payload = {
        "value": 26.8, "warm_tick_ms": 16.0, "platform": "cpu",
        "spec_hit_rate": 0.93, "breakdown": {"ignored": 1},
        "slo_overhead_pct": 1.2,
    }
    path = tmp_path / "h.jsonl"
    rec = append_history(payload, path, round_no=13)
    rec2 = append_history(payload, path)
    rows = load_history(path)
    assert len(rows) == 2
    assert rows[0]["round"] == 13 and rows[0]["value"] == 26.8
    assert "breakdown" not in rows[0]  # only HISTORY_KEYS ride along
    assert rows[0]["slo_overhead_pct"] == 1.2
    assert "round" not in rows[1]
    assert set(rec) - {"round", "captured_at"} <= set(HISTORY_KEYS)
    assert rec2["captured_at"]


# --------------------------------------------------------------------------
# stale_after_s — event-fed threshold series must be able to CLOSE


def _event_feed_spec(stale_after_s=None, suppress_warning=False):
    import warnings

    kw = {}
    if stale_after_s is not None:
        kw["stale_after_s"] = stale_after_s
    with warnings.catch_warnings():
        if suppress_warning:
            warnings.simplefilter("ignore")
        spec = SLOSpec(
            name="latency", kind="threshold", objective=0.9,
            series="openloop.latency_ms", threshold=100.0,
            alerts=[
                AlertRule(
                    severity="page",
                    windows=[BurnWindow(window_s=1.0, burn_rate=1.0)],
                    clear_hold_s=0.0,
                )
            ],
            **kw,
        )
        # Inside the catch block: pydantic re-validates the nested spec
        # (re-running its validator) when the parent model builds.
        return SLOConfig(slos=[spec])


def _stale_feed_timeline():
    """An event-fed latency series: every point bad, then traffic STOPS
    at t=5 — the exact shape that used to hold an alert open forever."""
    tl = Timeline()
    for i in range(101):
        tl.record(
            "openloop.latency_ms", i * 0.05, 500.0
        )  # last point at t=5.0
    return tl


def test_threshold_over_event_feed_warns_without_stale_horizon():
    with pytest.warns(UserWarning, match="event-fed"):
        _event_feed_spec()
    # A staleness horizon — or a continuously-sampled gauge series —
    # makes the spec closeable, so neither warns.
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        _event_feed_spec(stale_after_s=2.0)
        SLOSpec(
            name="p99", kind="threshold", objective=0.9,
            series="lat.gateway_event_to_placement.p99_ms", threshold=100.0,
        )


def test_event_feed_alert_holds_forever_without_stale_after():
    """The PR 13 gotcha, pinned as-is: once the window slides past the
    last point, a threshold spec with no horizon holds its open alert
    at every later evaluation — known behavior the fix exists for."""
    tl = _stale_feed_timeline()
    engine = SLOEngine(_event_feed_spec(suppress_warning=True), tl)
    assert [e["state"] for e in engine.evaluate(now=2.0)] == ["open"]
    for now in (6.0, 10.0, 100.0):
        assert engine.evaluate(now=now) == []
    assert len(engine.firing()) == 1  # still firing, forever


def test_event_feed_alert_opens_then_closes_with_stale_after():
    """With stale_after_s the same stale timeline transitions to
    KNOWN-idle once the feed's newest point ages out: error ratio 0.0,
    hysteresis runs, the alert CLOSES."""
    tl = _stale_feed_timeline()
    engine = SLOEngine(_event_feed_spec(stale_after_s=2.0), tl)
    assert [e["state"] for e in engine.evaluate(now=2.0)] == ["open"]
    # Window empty but the feed is not yet stale (6.0 - 5.0 < 2.0):
    # insufficient data still HOLDS — a brief lull must not close.
    assert engine.evaluate(now=6.5) == []
    assert len(engine.firing()) == 1
    # Past the horizon: known-idle, ratio 0.0, alert closes.
    assert [e["state"] for e in engine.evaluate(now=8.0)] == ["close"]
    assert engine.firing() == []
    # A series that never recorded is missing data, never known-idle.
    empty = Timeline()
    spec = _event_feed_spec(stale_after_s=2.0).slos[0]
    assert spec.error_ratio(empty, 1.0, now=10.0) is None

"""Crash-tolerant process tier (ISSUE 20 tentpole).

The gateway-side supervisor over ``ProcShardWorker``: crash detection on
the dead socket, respawn with bounded backoff, per-fleet WAL + micro-
snapshot recovery (exactly-once: WAL append BEFORE dispatch, snapshot
durable-rename THEN truncate, respawn restores warm and replays only the
tail), and the crash-loop breaker that quarantines a flapping worker and
re-homes its ring slice. All on the jax-free stub factory so the whole
file stays inside the tier-1 wall-clock budget — the real-scheduler
kill loop is ``make smoke-crash`` and the bench ``recovery`` section.
"""

from __future__ import annotations

import pytest

from distilp_tpu.gateway import Gateway
from distilp_tpu.gateway.procworker import WorkerCrashed
from distilp_tpu.gateway.traces import make_fleet_from_spec

FACTORY = "tests.procstub:make_scheduler"


def _supervised(
    tmp_path, n_fleets: int, n_workers: int = 1, snapshot_every: int = 2, **kw
) -> Gateway:
    gw = Gateway(
        n_workers=n_workers,
        scheduler_factory=FACTORY,
        worker_backend="process",
        supervise=True,
        recovery_dir=str(tmp_path),
        snapshot_every=snapshot_every,
        backoff_base_s=0.01,
        backoff_max_s=0.05,
        **kw,
    )
    for i in range(n_fleets):
        fid = f"r{i:02d}"
        gw.register_fleet(
            fid, make_fleet_from_spec(fid, {"m": 3, "seed": 900 + i}), "stub"
        )
    return gw


# -- exactly-once recovery -------------------------------------------------


def test_kill9_mid_stream_recovers_exactly_once(tmp_path):
    """A SIGKILL between ticks: the next dispatch walks into the dead
    child, recovery restores the snapshot + replays the WAL tail inline,
    and the interrupted event is applied exactly once (seq continuity,
    events_lost == 0) with every shard back WARM."""
    gw = _supervised(tmp_path, n_fleets=2)
    try:
        fleets = sorted(gw._fleet_key)
        for j in range(3):
            for fid in fleets:
                assert gw.handle_event(fid, f"ev{j}")["seq"] == j + 1
        gw.workers[0].kill_child()
        # The kill-adjacent event rides the recovery: no gap, no repeat.
        for fid in fleets:
            assert gw.handle_event(fid, "post-kill")["seq"] == 4
        rec = gw.recovery_status()
        assert rec["worker_crashes"] == 1
        assert rec["child_respawns"] == 1
        assert rec["shards_recovered"] == 2
        assert rec["events_lost"] == 0
        assert rec["cold_resumes"] == 0
        assert rec["warm_resumes"] == 2
        assert rec["workers_quarantined"] == 0
        assert rec["mttr_p99_ms"] > 0
    finally:
        gw.close()


def test_wal_replay_idempotent_across_double_crash(tmp_path):
    """Two kills with NO snapshot boundary between them: the second
    recovery replays a tail overlapping the first's. Replay reconciles
    record-by-record against the per-fleet cursor, so nothing is applied
    twice (seq stays strictly continuous, events_lost == 0 — a negative
    value here would mean double-apply)."""
    gw = _supervised(tmp_path, n_fleets=1)
    try:
        fid = sorted(gw._fleet_key)[0]
        for j in range(3):
            gw.handle_event(fid, f"ev{j}")
        gw.workers[0].kill_child()
        assert gw.handle_event(fid, "k1")["seq"] == 4
        # Immediate second kill: cursor 4 sits past the cursor-4
        # snapshot boundary taken during recovery; the replayed tails
        # overlap across the two recoveries.
        gw.workers[0].kill_child()
        assert gw.handle_event(fid, "k2")["seq"] == 5
        assert gw.handle_event(fid, "steady")["seq"] == 6
        rec = gw.recovery_status()
        assert rec["worker_crashes"] == 2
        assert rec["child_respawns"] == 2
        assert rec["events_lost"] == 0
        assert rec["cold_resumes"] == 0
    finally:
        gw.close()


def test_recovery_replays_only_the_wal_tail(tmp_path):
    """Micro-snapshots bound replay work: with snapshot_every=2 and the
    crash landing right after a boundary, the respawned child replays
    only the records past the snapshot cursor — not the fleet's whole
    history."""
    gw = _supervised(tmp_path, n_fleets=1)
    try:
        fid = sorted(gw._fleet_key)[0]
        for j in range(6):
            gw.handle_event(fid, f"ev{j}")
        gw.workers[0].kill_child()
        assert gw.handle_event(fid, "post")["seq"] == 7
        rec = gw.recovery_status()
        assert rec["events_lost"] == 0
        # Cursor 6 snapshot was durable before the kill: the tail is
        # AT MOST the post-boundary records, never the 6-event history.
        assert 0 < rec["events_replayed"] <= 2
        assert rec["micro_snapshots"] >= 3
    finally:
        gw.close()


def test_crash_during_recovery_replay_restarts_replay_idempotently(tmp_path):
    """The fresh child dies MID-REPLAY (after re-applying the first WAL
    record, before the second): the recovery loop classifies it as a new
    crash, respawns again, restores the SAME snapshot and replays the
    SAME tail from the top — the abandoned attempt's partial application
    died with its child, so nothing lands twice."""
    gw = _supervised(tmp_path, n_fleets=1, snapshot_every=4)
    try:
        fid = sorted(gw._fleet_key)[0]
        # Snapshot at cursor 1, WAL tail [2, 3]: two records to replay.
        for j in range(3):
            gw.handle_event(fid, f"ev{j}")
        worker = gw.workers[0]
        orig_rpc = worker.rpc
        state = {"killed": False}

        def chaos_rpc(req):
            out = orig_rpc(req)
            # First successful handle after the kill IS replay record #2
            # (the triggering dispatch died on the wire): kill again so
            # replaying record #3 walks into a second dead child.
            if req.get("method") == "handle" and not state["killed"]:
                state["killed"] = True
                worker.kill_child()
            return out

        worker.rpc = chaos_rpc
        worker.kill_child()
        assert gw.handle_event(fid, "post")["seq"] == 4
        assert state["killed"]  # the mid-replay kill actually fired
        rec = gw.recovery_status()
        assert rec["worker_crashes"] == 2
        assert rec["child_respawns"] == 2
        assert rec["events_lost"] == 0  # negative would mean double-apply
        assert rec["cold_resumes"] == 0
    finally:
        gw.close()


# -- crash-loop breaker ----------------------------------------------------


def test_crash_loop_breaker_quarantines_and_rebalances(tmp_path):
    """N crashes inside the window open the breaker: the flapping worker
    is quarantined (not respawned again), its ring slice re-homes onto
    the survivor, and serving continues with the seq chain intact."""
    gw = _supervised(
        tmp_path,
        n_fleets=4,
        n_workers=2,
        crash_loop_threshold=2,
        crash_loop_window_s=60.0,
    )
    try:
        fleets = sorted(gw._fleet_key)
        for j in range(2):
            for fid in fleets:
                gw.handle_event(fid, f"ev{j}")
        # Aim at whichever worker owns fleets[0]'s shard.
        key = gw._fleet_key[fleets[0]]
        wid = gw._shards[key][2]
        gw.workers[wid].kill_child()
        for fid in fleets:
            gw.handle_event(fid, "k1")  # crash 1 -> respawn
        gw.workers[wid].kill_child()
        for fid in fleets:
            assert gw.handle_event(fid, "k2")["seq"] == 4  # crash 2 -> breaker
        rec = gw.recovery_status()
        assert rec["workers_quarantined"] == 1
        assert rec["quarantined_workers"] == [wid]
        assert rec["events_lost"] == 0
        assert rec["cold_resumes"] == 0
        # The ring rebalanced away from the quarantined slot...
        assert gw.live_worker_ids() == [i for i in (0, 1) if i != wid]
        assert gw._shards[key][2] != wid
        # ...and the re-homed shards keep serving.
        for fid in fleets:
            assert gw.handle_event(fid, "steady")["seq"] == 5
    finally:
        gw.close()


def test_single_worker_gateway_never_quarantines(tmp_path):
    """With nowhere to re-home, the breaker keeps respawning past the
    threshold (documented): a 1-worker gateway must not serve nothing."""
    gw = _supervised(
        tmp_path,
        n_fleets=1,
        n_workers=1,
        crash_loop_threshold=1,
        crash_loop_window_s=60.0,
    )
    try:
        fid = sorted(gw._fleet_key)[0]
        gw.handle_event(fid, "ev0")
        for k in range(2):
            gw.workers[0].kill_child()
            assert gw.handle_event(fid, f"k{k}")["seq"] == k + 2
        rec = gw.recovery_status()
        assert rec["workers_quarantined"] == 0
        assert rec["child_respawns"] == 2
        assert rec["events_lost"] == 0
    finally:
        gw.close()


# -- RPC retry discipline --------------------------------------------------


def test_read_rpcs_retry_once_mutating_calls_never(tmp_path):
    """A read that dies on the wire retries ONCE against the respawned
    child (idempotent by definition); a mutating call never auto-retries
    — whether it applied child-side is ambiguous, and resolving that is
    the WAL's job, not a blind retry's."""
    gw = _supervised(tmp_path, n_fleets=1)
    try:
        fid = sorted(gw._fleet_key)[0]
        key = gw._fleet_key[fid]
        gw.handle_event(fid, "ev0")
        sched = gw.workers[0].shards[key]
        gw.workers[0].kill_child()
        # Read: recovered transparently, no exception, warm cursor intact.
        assert sched.latest()["seq"] == 1
        assert gw.recovery_status()["worker_crashes"] == 1
        # Mutation on a dead child: raises, never auto-retried.
        gw.workers[0].kill_child()
        with pytest.raises(WorkerCrashed) as ei:
            sched.handle("direct-mutation")
        assert ei.value.worker_id == 0
        # The supervised gateway path is how mutations recover (replay).
        assert gw.handle_event(fid, "ev1")["seq"] == 2
        assert gw.recovery_status()["events_lost"] == 0
    finally:
        gw.close()


# -- supervision off: byte-identical serving -------------------------------


def test_supervision_off_serving_is_byte_identical(tmp_path):
    """With supervise=False the recovery tier must be invisible: same
    views and same shard totals as the thread backend on the same trace,
    and no WAL/snapshot/supervision counter ever minted."""

    def run(backend: str, supervise: bool):
        kw = {}
        if supervise:
            kw = {"supervise": True, "recovery_dir": str(tmp_path)}
        gw = Gateway(
            n_workers=2,
            scheduler_factory=FACTORY,
            worker_backend=backend,
            **kw,
        )
        try:
            for i in range(3):
                fid = f"s{i:02d}"
                gw.register_fleet(
                    fid,
                    make_fleet_from_spec(fid, {"m": 3, "seed": 910 + i}),
                    "stub",
                )
            views = [
                gw.handle_event(f"s{i:02d}", f"ev{j}")
                for j in range(4)
                for i in range(3)
            ]
            counters = dict(gw.metrics.snapshot()["counters"])
            return views, gw.metrics_snapshot()["shard_totals"], counters
        finally:
            gw.close()

    views_t, totals_t, counters_t = run("thread", supervise=False)
    views_p, totals_p, counters_p = run("process", supervise=False)
    assert views_t == views_p
    assert totals_t == totals_p
    for counters in (counters_t, counters_p):
        for name in (
            "wal_appends",
            "micro_snapshots",
            "worker_crashes",
            "child_respawns",
            "shards_recovered",
            "events_replayed",
        ):
            assert name not in counters
    # Supervision ON serves the same views — the WAL rides alongside the
    # dispatch path, it never changes what a healthy tick returns.
    views_s, totals_s, counters_s = run("process", supervise=True)
    assert views_s == views_p
    assert totals_s == totals_p
    assert counters_s.get("wal_appends", 0) == 12


def test_unsupervised_recovery_status_and_crash_surface(tmp_path):
    """Supervision off: a child crash raises WorkerCrashed to the caller
    (typed — NOT RuntimeError's 409, NOT EOFError's 400) instead of
    being silently respawned."""
    gw = Gateway(
        n_workers=1, scheduler_factory=FACTORY, worker_backend="process"
    )
    try:
        gw.register_fleet(
            "u0", make_fleet_from_spec("u0", {"m": 3, "seed": 920}), "stub"
        )
        gw.handle_event("u0", "ev0")
        assert gw.recovery_status()["supervised"] is False
        gw.workers[0].kill_child()
        with pytest.raises(WorkerCrashed) as ei:
            gw.handle_event("u0", "ev1")
        assert not isinstance(ei.value, (RuntimeError, EOFError))
    finally:
        gw.close()


# -- satellite 1: migration abort folds the prefetched counters ------------


def test_migration_abort_folds_prefetched_counters(tmp_path):
    """Source child dies between the migration's prefetch and flip: the
    flip aborts, and the Phase-1 prefetched counter copy — the last
    readable one — folds into the fleet's running totals instead of
    dying with the child."""
    gw = Gateway(
        n_workers=2,
        scheduler_factory=FACTORY,
        worker_backend="process",
        dynamic=True,
    )
    try:
        for i in range(2):
            fid = f"m{i:02d}"
            gw.register_fleet(
                fid, make_fleet_from_spec(fid, {"m": 3, "seed": 930 + i}), "stub"
            )
        fleets = sorted(gw._fleet_key)
        for j in range(3):
            for fid in fleets:
                gw.handle_event(fid, f"ev{j}")
        fid = fleets[0]
        key = gw._fleet_key[fid]
        src_widx = gw._shards[key][2]
        src = gw.workers[src_widx]
        dst_widx = next(w for w in gw.live_worker_ids() if w != src_widx)
        # Arm the child to die on its SECOND dump from here: the
        # migration's prefetch dump succeeds, the flip dump crashes.
        dumps = src.rpc({"op": "getattr", "key": key, "name": "dumps"})
        src.rpc(
            {
                "op": "setattr",
                "key": key,
                "name": "exit_on_dump",
                "value": dumps + 2,
            }
        )
        with pytest.raises(WorkerCrashed):
            gw.migrate_shard(fid, dst_widx)
        assert gw.metrics.snapshot()["counters"]["migration_failed"] == 1
        # The abort path folded the prefetched copy: the fleet's events
        # survive the dead child in the running totals.
        assert gw._folded_counters[fid]["events_total"] == 3
    finally:
        gw.close()


# -- chaos plumbing --------------------------------------------------------


def test_crash_plan_fixture_parses_as_process_faults():
    from distilp_tpu.sched.faults import PROCESS_CHANNEL, FaultPlan

    plan = FaultPlan.from_json("tests/traces/crash_plan.json")
    assert plan.seed == 7
    kinds = [f.kind for f in plan.faults]
    assert "child_kill" in kinds and "rpc_delay" in kinds
    assert all(k in PROCESS_CHANNEL for k in kinds)


def test_chaos_replay_rejects_process_faults_without_hook():
    """A plan that schedules process-channel faults is only meaningful
    against a supervised process-backed gateway: chaos_replay must fail
    loudly, not silently skip the kills and report a clean soak."""
    from distilp_tpu.sched.faults import FaultPlan, chaos_replay
    from tests.procstub import StubScheduler

    plan = FaultPlan(
        seed=1, faults=[{"kind": "child_kill", "at_ticks": [0, 1]}]
    )
    with pytest.raises(ValueError, match="process_hook"):
        chaos_replay(StubScheduler([], "m"), ["ev0", "ev1"], plan)


# -- HTTP surface ----------------------------------------------------------


def test_http_maps_worker_crashed_to_503(tmp_path):
    """WorkerCrashed through POST /events is 503 + Retry-After (shard
    mid-recovery, back off and retry) — distinct from 409's 'nothing
    servable yet' and 400's client hangup — and mints its own counter."""
    import asyncio
    import json as _json
    import urllib.error
    import urllib.request

    from distilp_tpu.gateway.http import GatewayHTTPServer

    gw = Gateway(
        n_workers=1, scheduler_factory=FACTORY, worker_backend="process"
    )

    def post(port, path, payload):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=_json.dumps(payload).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return r.status, dict(r.headers), _json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), _json.loads(e.read())

    try:
        gw.register_fleet(
            "h0", make_fleet_from_spec("h0", {"m": 3, "seed": 940}), "stub"
        )

        async def main():
            srv = GatewayHTTPServer(gw)
            await srv.start()
            loop = asyncio.get_running_loop()
            port = srv.port
            ev = {"kind": "load", "t_comm_jitter": {}}
            st, _hdrs, out = await loop.run_in_executor(
                None, post, port, "/events", {"fleet": "h0", "event": ev}
            )
            assert st == 200 and out["view"]["seq"] == 1
            gw.workers[0].kill_child()
            st, hdrs, out = await loop.run_in_executor(
                None, post, port, "/events", {"fleet": "h0", "event": ev}
            )
            assert st == 503
            assert hdrs.get("Retry-After") == "1"
            assert out["worker"] == 0
            counters = gw.metrics.snapshot()["counters"]
            assert counters["http_worker_crashed"] == 1
            assert "http_internal_error" not in counters
            await srv.close()

        asyncio.run(main())
    finally:
        gw.close()

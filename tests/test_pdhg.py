"""PDHG engine tests: kernel soundness vs scipy, engine parity vs the IPM
and the HiGHS oracle, warm-state interchange, and lp_backend plumbing.

The fleet-scale contract (ISSUE 6): the matrix-free restarted Halpern PDHG
engine must be drop-in interchangeable with the IPM behind ``backend_jax``
— same ``LPBatch`` in, same ``IPMResult`` out, same warm-state fields, same
rigorous f64 Lagrangian bound — so everything downstream (branch-and-bound
pruning, certification, streaming warm starts, the scheduler) is engine-
agnostic. These tests pin each face of that contract.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from distilp_tpu.common import load_from_profile_folder, load_model_profile  # noqa: E402
from distilp_tpu.ops import (  # noqa: E402
    IPMWarmState,
    LPBatch,
    PDHGWarmState,
    ipm_solve_batch,
    pdhg_solve_batch,
)
from distilp_tpu.solver import halda_solve  # noqa: E402
from distilp_tpu.solver.streaming import StreamingReplanner  # noqa: E402
from distilp_tpu.utils import make_synthetic_fleet  # noqa: E402

GAP = 1e-3


def _random_feasible_batch(rng, m, n, B, fix_frac=0.2):
    from scipy.optimize import linprog

    A = rng.normal(size=(m, n))
    bs, cs, ls, us, refs = [], [], [], [], []
    for _ in range(B):
        l = rng.uniform(-2, 0, n)
        u = l + rng.uniform(0.5, 3, n)
        fix = rng.random(n) < fix_frac
        u = np.where(fix, l, u)
        x_feas = l + rng.uniform(0, 1, n) * (u - l)
        b = A @ x_feas
        c = rng.normal(size=n)
        r = linprog(c, A_eq=A, b_eq=b, bounds=np.stack([l, u], 1), method="highs")
        assert r.status == 0
        refs.append(r.fun)
        bs.append(b)
        cs.append(c)
        ls.append(l)
        us.append(u)
    batch = LPBatch(
        jnp.array(A), jnp.array(bs), jnp.array(cs), jnp.array(ls), jnp.array(us)
    )
    return batch, np.array(refs)


# --------------------------------------------------------------------------
# Kernel level — mirrors tests/test_ipm.py so the two engines pin the SAME
# contract. Tolerances are first-order-appropriate: PDHG trades the IPM's
# quadratic tail for factorization-free iterations, so optimality agreement
# is asserted at 1e-5/1e-6 instead of the IPM's 1e-8; bound VALIDITY is
# exact in both (the f64 certificate holds for any dual).


def test_pdhg_matches_scipy_on_random_lps():
    rng = np.random.default_rng(42)
    batch, refs = _random_feasible_batch(rng, m=10, n=25, B=16)
    # 40k budget: the hardest of the 16 random LPs needs ~25k first-order
    # iterations to the 1e-9 exit — the tight-tolerance tail is exactly
    # what the engine's own default (1e-7) exists to avoid paying.
    res = pdhg_solve_batch(batch, iters=40000, tol=1e-9)
    assert np.all(np.array(res.converged))
    np.testing.assert_allclose(np.array(res.obj), refs, rtol=1e-6, atol=1e-6)
    # The Lagrangian bound must be a valid lower bound on the true optimum.
    assert np.all(np.array(res.bound) <= refs + 1e-6)
    np.testing.assert_allclose(np.array(res.bound), refs, rtol=1e-5, atol=1e-5)


def test_pdhg_all_columns_fixed():
    """A fully-fixed box (every variable pinned) must not blow up."""
    rng = np.random.default_rng(3)
    n, m = 8, 3
    A = rng.normal(size=(m, n))
    l = rng.uniform(0, 1, size=(1, n))
    u = l.copy()
    b = (A @ l[0])[None, :]
    c = rng.normal(size=(1, n))
    res = pdhg_solve_batch(
        LPBatch(jnp.array(A), jnp.array(b), jnp.array(c), jnp.array(l), jnp.array(u)),
        iters=50,
    )
    assert np.isfinite(float(res.obj[0]))
    assert float(res.obj[0]) == pytest.approx(float(c[0] @ l[0]))


def test_pdhg_warm_start_matches_cold_and_early_exits():
    """A warm-started solve reaches the cold solve's objective in strictly
    fewer iterations — the contract the B&B node-iterate and streaming
    root-warm plumbing relies on, for either engine."""
    rng = np.random.default_rng(11)
    batch, refs = _random_feasible_batch(rng, m=10, n=25, B=12)
    cold = pdhg_solve_batch(batch, iters=20000, tol=1e-8)
    assert np.all(np.array(cold.converged))
    warm_state = PDHGWarmState(
        v=cold.v, y=cold.y_dual, z=cold.z_dual, f=cold.f_dual,
        ok=jnp.ones(12, bool),
    )
    warm = pdhg_solve_batch(batch, iters=20000, tol=1e-8, warm=warm_state)
    assert np.all(np.array(warm.converged))
    np.testing.assert_allclose(
        np.array(warm.obj), np.array(cold.obj), rtol=1e-5, atol=1e-6
    )
    assert np.all(np.array(warm.bound) <= refs + 1e-6)
    assert np.array(warm.iters_run).max() < np.array(cold.iters_run).max()


def test_pdhg_truncated_budget_bound_stays_sound():
    """An early-truncated PDHG solve must still return a rigorous float64
    lower bound — branch-and-bound prunes on it, so this is the soundness
    half of running first-order relaxations inside the search."""
    rng = np.random.default_rng(21)
    batch, refs = _random_feasible_batch(rng, m=10, n=25, B=12)
    for iters in (5, 20, 100, 500):
        res = pdhg_solve_batch(batch, iters=iters, chunk=5)
        b = np.array(res.bound)
        assert np.all(np.isfinite(b) | np.isneginf(b))
        assert np.all(b <= refs + 1e-6), f"unsound bound at iters={iters}"


def test_pdhg_garbage_warm_state_degrades_to_cold():
    """NaN/inf warm components fall back to the cold start wholesale;
    finite-but-absurd warm points still converge to the cold result — a
    stale streaming iterate can cost iterations, never correctness."""
    rng = np.random.default_rng(33)
    B = 8
    batch, refs = _random_feasible_batch(rng, m=10, n=25, B=B)
    cold = pdhg_solve_batch(batch, iters=20000, tol=1e-8)

    bad = PDHGWarmState(
        v=jnp.full_like(cold.v, jnp.nan),
        y=jnp.full_like(cold.y_dual, jnp.inf),
        z=cold.z_dual,
        f=cold.f_dual,
        ok=jnp.ones(B, bool),
    )
    res = pdhg_solve_batch(batch, iters=20000, tol=1e-8, warm=bad)
    np.testing.assert_allclose(
        np.array(res.obj), np.array(cold.obj), rtol=1e-6, atol=1e-7
    )

    absurd = PDHGWarmState(
        v=1e6 * jnp.ones_like(cold.v),
        y=-1e5 * jnp.ones_like(cold.y_dual),
        z=1e9 * jnp.ones_like(cold.z_dual),
        f=1e-12 * jnp.ones_like(cold.f_dual),
        ok=jnp.ones(B, bool),
    )
    res2 = pdhg_solve_batch(batch, iters=40000, tol=1e-8, warm=absurd)
    assert np.all(np.array(res2.converged))
    np.testing.assert_allclose(
        np.array(res2.obj), np.array(cold.obj), rtol=1e-5, atol=1e-6
    )
    assert np.all(np.array(res2.bound) <= refs + 1e-6)

    # ok=False must behave exactly like no warm state at all.
    off = PDHGWarmState(
        v=absurd.v, y=absurd.y, z=absurd.z, f=absurd.f,
        ok=jnp.zeros(B, bool),
    )
    res3 = pdhg_solve_batch(batch, iters=20000, tol=1e-8, warm=off)
    np.testing.assert_allclose(
        np.array(res3.obj), np.array(cold.obj), rtol=1e-9, atol=1e-10
    )


def test_pdhg_skip_mask_freezes_elements():
    """Skipped elements execute zero iterations and never gate the batch
    early exit (inactive frontier rows ride this)."""
    rng = np.random.default_rng(44)
    B = 6
    batch, _ = _random_feasible_batch(rng, m=8, n=18, B=B)
    sk = jnp.zeros(B, bool).at[2].set(True)
    res = pdhg_solve_batch(batch, iters=40000, tol=1e-8, skip=sk)
    runs = np.array(res.iters_run)
    assert runs[2] == 0
    live = np.delete(np.arange(B), 2)
    assert np.all(runs[live] > 0)
    assert np.all(np.array(res.converged)[live])


def test_pdhg_infeasible_bound_grows():
    """On an infeasible LP the Lagrangian bound exceeds any feasible-looking
    value, so branch-and-bound prunes the node — same contract as the IPM."""
    A = jnp.array([[1.0, 1.0]])
    b = jnp.array([[10.0]])  # x1 + x2 = 10 but boxes cap at 2
    c = jnp.array([[1.0, 1.0]])
    l = jnp.zeros((1, 2))
    u = jnp.full((1, 2), 1.0)
    res = pdhg_solve_batch(LPBatch(A, b, c, l, u), iters=5000)
    assert float(res.bound[0]) > 2.0


def test_warm_states_interchange_between_engines():
    """The cross-engine half of the shared-warm-start contract: an IPM
    result warm-starts PDHG and a PDHG result warm-starts the IPM, both
    landing on the same optimum. This is what lets `auto` flip engines
    between streaming ticks without dropping the carried iterates."""
    rng = np.random.default_rng(55)
    B = 8
    batch, refs = _random_feasible_batch(rng, m=10, n=25, B=B)
    ipm_res = ipm_solve_batch(batch, iters=60)
    assert np.all(np.array(ipm_res.converged))

    # IPM iterate -> PDHG warm (PDHGWarmState and IPMWarmState are
    # field-for-field identical; use each engine's own type to prove both
    # constructors accept the other's payload).
    p_from_i = pdhg_solve_batch(
        batch, iters=20000, tol=1e-8,
        warm=PDHGWarmState(
            v=ipm_res.v, y=ipm_res.y_dual, z=ipm_res.z_dual,
            f=ipm_res.f_dual, ok=jnp.ones(B, bool),
        ),
    )
    assert np.all(np.array(p_from_i.converged))
    np.testing.assert_allclose(np.array(p_from_i.obj), refs, rtol=1e-5, atol=1e-5)

    pdhg_res = pdhg_solve_batch(batch, iters=20000, tol=1e-8)
    i_from_p = ipm_solve_batch(
        batch, iters=60,
        warm=IPMWarmState(
            v=pdhg_res.v, y=pdhg_res.y_dual, z=pdhg_res.z_dual,
            f=pdhg_res.f_dual, ok=jnp.ones(B, bool),
        ),
    )
    assert np.all(np.array(i_from_p.converged))
    np.testing.assert_allclose(np.array(i_from_p.obj), refs, rtol=1e-7, atol=1e-7)
    # A converged first-order point is a USEFUL barrier seed, not just a
    # tolerated one: the warm IPM solve must beat the cold one's work.
    cold_ipm = ipm_solve_batch(batch, iters=60)
    assert (
        np.array(i_from_p.iters_run).max()
        <= np.array(cold_ipm.iters_run).max()
    )


# --------------------------------------------------------------------------
# Engine parity end-to-end: PDHG vs IPM vs the HiGHS oracle through
# halda_solve on the golden fixtures and the north-star fleet.

GOLDEN = [
    ("hermes_70b", 40, 29.643569),
    ("llama_3_70b/4bit", 8, 12.834690),
    ("llama_3_70b/online", 2, 1.934942),
    ("qwen3_32b/bf16", 16, 12.072837),
]


@pytest.mark.parametrize("folder,k_star,obj", GOLDEN)
def test_pdhg_backend_matches_golden(profiles_dir, folder, k_star, obj):
    """lp_backend='pdhg' certifies the same optimum as the committed golden
    objectives (themselves pinned against HiGHS) on every dense fixture."""
    devs, model = load_from_profile_folder(profiles_dir / folder)
    result = halda_solve(
        devs, model, mip_gap=1e-4, kv_bits="4bit", backend="jax",
        lp_backend="pdhg",
    )
    assert result.k == k_star
    assert result.obj_value == pytest.approx(obj, rel=2e-4)
    assert sum(result.w) * result.k == model.L
    for wi, ni in zip(result.w, result.n):
        assert 0 <= ni <= wi


def test_pdhg_matches_ipm_and_cpu_on_north_star(profiles_dir):
    """The three-way agreement the ISSUE names: PDHG == IPM == HiGHS within
    mip_gap on the 16-device north-star fleet, with the engine echo
    confirming which engine actually ran."""
    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(16, seed=123)
    ref = halda_solve(devs, model, mip_gap=GAP, kv_bits="4bit", backend="cpu")
    tm_i: dict = {}
    ipm = halda_solve(
        devs, model, mip_gap=GAP, kv_bits="4bit", backend="jax",
        lp_backend="ipm", timings=tm_i,
    )
    tm_p: dict = {}
    pdhg = halda_solve(
        devs, model, mip_gap=GAP, kv_bits="4bit", backend="jax",
        lp_backend="pdhg", timings=tm_p,
    )
    assert tm_i["lp_backend"] == "ipm"
    assert tm_p["lp_backend"] == "pdhg"
    assert ipm.certified and pdhg.certified
    assert pdhg.obj_value == pytest.approx(ref.obj_value, rel=2 * GAP)
    assert pdhg.obj_value == pytest.approx(ipm.obj_value, rel=2 * GAP)
    assert sum(pdhg.w) * pdhg.k == model.L
    assert all(0 <= n <= w for w, n in zip(pdhg.w, pdhg.n))


def test_auto_resolves_by_fleet_size():
    """'auto' picks the IPM below PDHG_AUTO_M and PDHG at/above it —
    resolved once per solve and echoed in timings."""
    from distilp_tpu.solver.backend_jax import (
        PDHG_AUTO_M,
        _resolve_lp_backend,
    )

    assert _resolve_lp_backend(None, 16) == "ipm"
    assert _resolve_lp_backend("auto", PDHG_AUTO_M - 1) == "ipm"
    assert _resolve_lp_backend("auto", PDHG_AUTO_M) == "pdhg"
    assert _resolve_lp_backend("ipm", 4096) == "ipm"
    assert _resolve_lp_backend("pdhg", 2) == "pdhg"
    with pytest.raises(ValueError, match="lp_backend"):
        _resolve_lp_backend("simplex", 16)


def test_pdhg_warm_tick_via_streaming(profiles_dir):
    """lp_backend rides StreamingReplanner's search overrides: warm ticks
    under the PDHG engine certify and agree with a cold HiGHS solve of the
    drifted instance — the engine-agnostic streaming warm-start contract."""
    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(16, seed=123)
    planner = StreamingReplanner(
        mip_gap=GAP, kv_bits="4bit", backend="jax",
        search={"lp_backend": "pdhg"},
    )
    first = planner.step(devs, model)
    assert first.certified
    rng = np.random.default_rng(7)
    for d in devs:
        d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
    warm = planner.step(devs, model)
    assert warm.certified
    cold = halda_solve(devs, model, mip_gap=GAP, kv_bits="4bit", backend="cpu")
    assert abs(warm.obj_value - cold.obj_value) <= 2 * GAP * abs(cold.obj_value)


def test_lp_backend_plumbs_through_scheduler(profiles_dir):
    """`serve --lp-backend` reaches the solves: the scheduler's replanners
    inherit the engine and the per-tick engine echo is counted in the
    metrics snapshot."""
    from distilp_tpu.profiler.api import profile_model
    from distilp_tpu.sched.events import LoadTick
    from distilp_tpu.sched.scheduler import Scheduler

    model = profile_model(
        "tests/configs/llama31_8b_4bit.json", batch_sizes=[1],
        sequence_length=128,
    ).to_model_profile()
    devs = make_synthetic_fleet(4, seed=11)
    sched = Scheduler(
        devs, model, mip_gap=GAP, kv_bits="4bit", backend="jax",
        k_candidates=[4, 8], lp_backend="pdhg",
    )
    try:
        view = sched.handle(LoadTick(t_comm_jitter={}))
        assert view.result.certified
        c = sched.metrics.counters
        assert c["lp_backend_pdhg"] >= 1
        assert c["lp_backend_ipm"] == 0
    finally:
        sched.close()


def test_pdhg_iters_knob_plumbed(profiles_dir):
    """pdhg_iters reaches the device program: a starved budget loosens the
    bound into an uncertified return (warning), the default certifies —
    the same truncation-only-loosens contract as ipm_iters."""
    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(8, seed=8)
    # 20 iterations finds a feasible incumbent but cannot close a 1e-4 gap
    # in one round (a harder starvation — pdhg_iters≈3 — rounds NOTHING
    # feasible and raises instead, which is the other honest outcome).
    with pytest.warns(RuntimeWarning, match="certificate NOT met"):
        short = halda_solve(
            devs, model, mip_gap=1e-4, kv_bits="4bit", backend="jax",
            lp_backend="pdhg", pdhg_iters=20, max_rounds=1,
        )
    assert not short.certified
    full = halda_solve(
        devs, model, mip_gap=1e-4, kv_bits="4bit", backend="jax",
        lp_backend="pdhg",
    )
    assert full.certified

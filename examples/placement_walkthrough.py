#!/usr/bin/env python3
"""End-to-end tour of distilp_tpu: profile -> solve -> stream -> route.

Runs on any JAX backend (CPU included) in ~a minute; no weights are
downloaded — model profiling is analytic from a config.json. Each stage
prints what it produced. See README.md for the concepts.

    python examples/placement_walkthrough.py
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main() -> int:
    import numpy as np

    from distilp_tpu.axon_guard import force_cpu_if_env_requested

    force_cpu_if_env_requested()  # JAX_PLATFORMS=cpu must not wedge on a
    #                               dead tunneled-TPU plugin (see axon_guard)

    # Step 19 runs a row-sharded solve on a 4-device mesh. On a CPU-only
    # box jax exposes ONE device unless the host-platform split flag is in
    # the environment before the backend initializes — so it must go in
    # here, before the first solve, not at step 19 (utils/shardcompat.py).
    from distilp_tpu.utils import shardcompat

    shardcompat.force_host_devices(4)

    from distilp_tpu.profiler.api import profile_model
    from distilp_tpu.solver import (
        StreamingReplanner,
        halda_solve,
        solve_load_aware,
    )
    from distilp_tpu.utils import make_synthetic_fleet

    # ------------------------------------------------------------------
    # 1. Model profile: analytic walk of the architecture (config-only).
    # ------------------------------------------------------------------
    split = profile_model(
        str(REPO / "tests" / "configs" / "mixtral_8x7b.json"),
        batch_sizes=[1],
        sequence_length=128,
    )
    model = split.to_model_profile()
    print(
        f"[1] profiled Mixtral-8x7B: L={model.L} layers, "
        f"E={model.n_routed_experts} routed experts, "
        f"~{model.b_layer / 2**20:.0f} MiB per dense-equivalent layer"
    )

    # ------------------------------------------------------------------
    # 2. Fleet: heterogeneous devices (usually one JSON per machine from
    #    `profiler device`; synthetic here).
    # ------------------------------------------------------------------
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    print(f"[2] fleet: {[d.name for d in devs]}")

    # ------------------------------------------------------------------
    # 3. One certified solve: pipeline segments (k), per-device layer
    #    windows (w), GPU-resident layers (n), hosted experts (y).
    # ------------------------------------------------------------------
    result = halda_solve(devs, model, kv_bits="8bit", mip_gap=1e-3, backend="jax")
    print(
        f"[3] solved: k={result.k} w={result.w} n={result.n} y={result.y} "
        f"obj={result.obj_value:.4f} certified={result.certified} "
        f"(gap {result.gap:.2e})"
    )

    # ------------------------------------------------------------------
    # 4. Streaming re-placement: profiles drift, ticks re-solve warm.
    # ------------------------------------------------------------------
    planner = StreamingReplanner(mip_gap=1e-3, kv_bits="8bit", backend="jax")
    planner.step(devs, model)
    rng = np.random.default_rng(0)
    for tick in range(3):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.9, 1.1)))
        r = planner.step(devs, model)
        print(
            f"[4] tick {tick}: obj={r.obj_value:.4f} "
            f"certified={r.certified} y={r.y}"
        )

    # ------------------------------------------------------------------
    # 4b. Pipelined ticks: keep one solve in flight; the next tick's
    #     instance assembly and upload overlap the previous solve's
    #     execution and result transfer (throughput > 1/RTT on tunnels).
    # ------------------------------------------------------------------
    planner.reset()
    planner.submit(devs, model)
    for tick in range(2):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
        planner.submit(devs, model)
        r = planner.collect()
        print(f"[4b] pipelined tick {tick}: certified={r.certified}")
    planner.collect()

    # ------------------------------------------------------------------
    # 5. Load-weighted routing: two experts carry half the traffic; the
    #    mapper sends them to fast devices and the solver re-prices.
    # ------------------------------------------------------------------
    E = model.n_routed_experts
    loads = [4.0, 4.0] + [1.0] * (E - 2)
    routed, mapping, realized = solve_load_aware(
        devs, model, expert_loads=loads, kv_bits="8bit", mip_gap=1e-3,
        backend="jax",
    )
    print(f"[5] load-aware: y={routed.y} realized objective={realized:.4f}")
    for d, ids, share in zip(devs, mapping.expert_of_device, mapping.load_share):
        print(f"    {d.name:28s} experts={ids} ({share * 100:4.1f}% of load)")

    # ------------------------------------------------------------------
    # 6. Scenario batching: what-if t_comm futures of the SAME fleet solve
    #    in ONE device dispatch (shared static half, vmapped search) —
    #    S placements for ~one placement's wire time on a tunneled chip.
    # ------------------------------------------------------------------
    from distilp_tpu.solver import halda_solve_scenarios

    futures = []
    for scale in (1.0, 2.0, 0.5):  # now / link degrades / link improves
        snap = [d.model_copy(deep=True) for d in devs]
        for d in snap:
            d.t_comm = max(0.0, d.t_comm * scale)
        futures.append(snap)
    what_ifs = halda_solve_scenarios(
        futures, model, kv_bits="8bit", mip_gap=1e-3
    )
    for label, r in zip(("now", "2x t_comm", "0.5x t_comm"), what_ifs):
        print(
            f"[6] scenario {label:>10s}: k={r.k} obj={r.obj_value:.4f} "
            f"certified={r.certified}"
        )

    # ------------------------------------------------------------------
    # 7. The full k-curve: every feasible segment count solved to its own
    #    certificate in one dispatch (capacity planning: what would a
    #    different pipeline depth cost?).
    # ------------------------------------------------------------------
    from distilp_tpu.solver import halda_solve_per_k

    per_k = halda_solve_per_k(devs, model, kv_bits="8bit", mip_gap=1e-3)
    for r in per_k:
        print(
            f"[7] k={r.k}: obj={r.obj_value:.4f} certified={r.certified} "
            f"y={r.y}"
        )

    # ------------------------------------------------------------------
    # 8. Digital twin: execute the placement instead of trusting the
    #    proxy — deterministic simulated run (must agree with the
    #    objective), then a 512-sample vmapped Monte-Carlo robustness
    #    report (latency tail under device drift + stragglers, memory
    #    feasibility, worst-device sensitivity), one JAX dispatch.
    # ------------------------------------------------------------------
    from distilp_tpu.twin import evaluate_placement, rank_agreement, robustness_report

    # Evaluate the k-curve winner: it was solved against the CURRENT
    # profiles (steps 4-5 drifted t_comm since step 3's solve, and the
    # twin prices whatever the profiles say now).
    best = min(per_k, key=lambda r: r.obj_value)
    ev = evaluate_placement(devs, model, best, kv_bits="8bit")
    print(
        f"[8] twin: latency={ev.latency_s:.4f}s vs objective="
        f"{ev.objective_s:.4f}s (rel err {ev.rel_err:.1e}), "
        f"bottleneck={ev.bottleneck}"
    )
    rep = robustness_report(
        devs, model, best, samples=512, seed=0, kv_bits="8bit",
        dropout_p=0.05,
    )
    print(
        f"[8] robustness: p50={rep.p50_s:.4f}s p95={rep.p95_s:.4f}s "
        f"p99={rep.p99_s:.4f}s P(mem violation)={rep.p_violation:.3f}"
    )
    print(
        f"[8] most latency-critical device: {rep.sensitivity[0].name} "
        f"(+{rep.sensitivity[0].delta_s:.4f}s under a 1.25x slowdown)"
    )
    if len(per_k) >= 2:
        ra = rank_agreement(devs, model, per_k, kv_bits="8bit")
        print(
            f"[8] twin-vs-objective rank agreement over the k-curve: "
            f"spearman={ra['spearman']:.3f} "
            f"({ra['pairwise_inversions']} inversions)"
        )

    # ------------------------------------------------------------------
    # 9. Fleet scale: past ~a hundred devices the IPM's dense per-node
    #    normal matrices stop fitting, so `lp_backend='auto'` (the default
    #    everywhere above) switches to the matrix-free restarted Halpern
    #    PDHG engine — same warm-start plumbing, same f64 Lagrangian
    #    certificate, no factorizations (README "LP backends"). HALDA
    #    places every device (w_i >= 1), so a fleet-scale instance needs a
    #    model at least as deep as the fleet: stretch the 70B profile's
    #    typical-layer scalars to 2M layers (the same synthetic-instance
    #    recipe as bench.py's fleet_scale section) and solve a 160-device
    #    fleet, engine chosen automatically and echoed in timings.
    # ------------------------------------------------------------------
    from distilp_tpu.common import load_model_profile
    from distilp_tpu.utils import stretch_model_for_fleet

    M_big = 160
    big_model = stretch_model_for_fleet(load_model_profile(
        REPO / "tests" / "profiles" / "llama_3_70b" / "online"
        / "model_profile.json"
    ), M_big)
    big_fleet = make_synthetic_fleet(M_big, seed=42)
    tm: dict = {}
    big = halda_solve(
        big_fleet, big_model, kv_bits="4bit", mip_gap=1e-3, backend="jax",
        timings=tm,
    )
    print(
        f"[9] fleet-scale solve (M={M_big}, L={big_model.L}): "
        f"engine={tm['lp_backend']} k={big.k} obj={big.obj_value:.4f} "
        f"certified={big.certified} solve={tm['solve_ms']:.0f}ms"
    )

    # ------------------------------------------------------------------
    # 10. Scaling out: the gateway tier serves MANY fleets at once — each
    #     (fleet, model) shard owned by exactly one solve worker
    #     (consistent hash), every shard its own warm pool and health
    #     state. Replay 10 synthetic fleets through 2 workers, snapshot
    #     the whole tier's warm state mid-trace (drain -> one JSON blob:
    #     incumbents, duals, LP iterates, margin anchors), "crash", then
    #     restore into a FRESH gateway and finish the trace: the restored
    #     run resumes with warm ticks — zero cold re-solves — and lands
    #     on the same placements an uninterrupted run produces
    #     (README "Scaling out"; `make smoke-gateway` gates this).
    # ------------------------------------------------------------------
    from distilp_tpu.gateway import Gateway, GatewaySnapshot
    from distilp_tpu.gateway.loadgen import make_fleet_specs, make_loadgen_trace
    from distilp_tpu.gateway.traces import make_fleet_from_spec

    gw_model = load_model_profile(
        REPO / "tests" / "profiles" / "llama_3_70b" / "online"
        / "model_profile.json"
    )
    specs = make_fleet_specs(10, fleet_size=3, seed=42)
    items = make_loadgen_trace(specs, 3, seed=42)  # 10 fleets x 3 drifts
    gw_kwargs = dict(
        mip_gap=1e-3, kv_bits="4bit", backend="jax", k_candidates=[8, 10]
    )

    import json as _json

    gw = Gateway(n_workers=2, scheduler_kwargs=gw_kwargs)
    for fid, spec in specs.items():
        gw.register_fleet(fid, make_fleet_from_spec(fid, spec), gw_model)
    for fid, ev in items[:15]:  # first half of the trace...
        gw.handle_event(fid, ev)
    snapshot = gw.snapshot()  # ...drain + serialize every shard's warm state
    gw.close()  # "crash": the process state is gone, only the blob remains
    wire = _json.dumps(snapshot.model_dump())
    print(
        f"[10] gateway: snapshot of {len(snapshot.shards)} shards after 15 "
        f"events ({len(wire) // 1024} KB)"
    )

    restored = Gateway(n_workers=2, scheduler_kwargs=gw_kwargs)
    restored.load_snapshot(GatewaySnapshot.model_validate(_json.loads(wire)))
    for fid, ev in restored.uncovered(items):  # only the uncovered suffix
        restored.handle_event(fid, ev)
    totals = restored.metrics_snapshot()["shard_totals"]
    print(
        f"[10] restored + finished trace: warm_resumes="
        f"{totals['warm_resumes']}/10 cold_resumes={totals['cold_resumes']} "
        f"tick_cold={totals['tick_cold']} (zero-downtime contract: all "
        "restored shards resume warm)"
    )
    restored.close()

    # ------------------------------------------------------------------
    # 11. Observability: where did each event's time go? Replay the
    #     bundled 10-fleet gateway trace with span tracing on (`serve
    #     --trace-spans-dir`), convert the span JSONL with `solver spans`
    #     into Chrome trace-event JSON (load it in ui.perfetto.dev — one
    #     track per worker thread, queue waits drawn as flow arrows), and
    #     print the top-3 slowest spans (README "Observability").
    # ------------------------------------------------------------------
    import tempfile

    from distilp_tpu.cli.solver_cli import serve_main, spans_main
    from distilp_tpu.obs import read_spans, top_spans

    with tempfile.TemporaryDirectory(prefix="distilp-obs-") as obs_dir:
        rc = serve_main(
            [
                "--trace",
                str(REPO / "tests" / "traces" / "gateway_smoke_10f.jsonl"),
                "--profile",
                str(REPO / "tests" / "profiles" / "llama_3_70b" / "online"),
                "--workers", "2", "--k-candidates", "8,10", "--quiet",
                "--trace-spans-dir", obs_dir,
            ]
        )
        if rc != 0:
            print(f"[11] traced replay failed (rc={rc})")
            return rc
        spans_path = Path(obs_dir) / "spans.jsonl"
        spans = read_spans(spans_path)
        spans_main([str(spans_path), "--quiet"])
        chrome = spans_path.with_suffix(".chrome.json")
        print(
            f"[11] traced gateway replay: {len(spans)} spans from "
            f"{len({s['trace_id'] for s in spans})} events -> "
            f"{chrome.name} ({chrome.stat().st_size // 1024} KB Perfetto "
            "file); top-3 slowest spans:"
        )
        for s in top_spans(spans, 3):
            attrs = s.get("attrs") or {}
            extra = "".join(
                f" {k}={attrs[k]}"
                for k in ("fleet", "kind", "mode", "lp_backend")
                if k in attrs
            )
            print(
                f"[11]   {s['dur_ms']:9.1f} ms  {s['name']:<18s} "
                f"thread={s['thread']}{extra}"
            )

    # ------------------------------------------------------------------
    # 12. Speculative replanning: churn is predictable, so stop paying a
    #     solve for it. Replay the bundled burst trace (correlated
    #     multi-device t_comm spikes that relax exactly) twice — plain,
    #     then with --speculate: the scheduler forecasts the likely next
    #     states from the applied event stream, pre-solves them as ONE
    #     vmapped scenario batch after each tick (off the serving path),
    #     and serves a matching event straight from the placement bank
    #     (mode='spec') at cache-hit latency. Honest misses fall through
    #     to the normal tick path (README "Speculative replanning";
    #     `make smoke-spec` gates this).
    # ------------------------------------------------------------------
    from distilp_tpu.sched import Scheduler, read_trace
    from distilp_tpu.sched.metrics import _quantile

    spec_events = read_trace(REPO / "tests" / "traces" / "spec_burst.jsonl")
    spec_model = load_model_profile(
        REPO / "tests" / "profiles" / "llama_3_70b" / "online"
        / "model_profile.json"
    )
    warmup = 12  # jit compiles + the cold-bank misses while learning
    stats = {}
    for speculate in (False, True):
        sched = Scheduler(
            make_synthetic_fleet(4, seed=11), spec_model, mip_gap=1e-3,
            kv_bits="4bit", backend="jax", k_candidates=[8, 10],
            speculative=speculate,
        )
        lat = []
        for i, ev in enumerate(spec_events):
            view = sched.handle(ev)
            if i >= warmup and view.events_behind == 0:
                lat.append(sched.last_serve_ms)
        stats[speculate] = {
            "p50": _quantile(sorted(lat), 0.50),
            "p99": _quantile(sorted(lat), 0.99),
            "spec": sched.speculation_snapshot(),
        }
        sched.close()
    on, off = stats[True], stats[False]
    sp = on["spec"]
    print(
        f"[12] speculation off: p50={off['p50']:.2f}ms p99={off['p99']:.2f}ms"
        f" | on: p50={on['p50']:.3f}ms p99={on['p99']:.3f}ms "
        f"({len(spec_events)} events, steady state)"
    )
    print(
        f"[12] bank: {sp['hits']}/{sp['hits'] + sp['misses']} ticks served "
        f"pre-solved (hit rate {100 * sp['hit_rate']:.0f}%, "
        f"{sp['presolved']} futures pre-solved) — event->placement p99 "
        f"{off['p99'] / max(on['p99'], 1e-9):.0f}x lower with speculation"
    )

    # ------------------------------------------------------------------
    # 13. Convergence diagnostics: when a solve misses its certificate,
    #     the solver-interior telemetry says WHY. Starve the round budget
    #     on purpose (max_rounds=2 at a tight 1e-5 gap) and read the
    #     per-round search log the jitted loop recorded about itself —
    #     then give the full budget back and watch the gap close round by
    #     round (README "Convergence diagnostics"; `solver diagnose` is
    #     the CLI over the same report, `make smoke-diag` gates it).
    # ------------------------------------------------------------------
    import warnings

    from distilp_tpu.obs import build_search_trace

    conv = {}
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # the certificate miss is the point
        starved = halda_solve(
            devs, model, kv_bits="8bit", mip_gap=1e-5, backend="jax",
            max_rounds=2, convergence=conv,
        )
    tr = build_search_trace(conv)
    print(
        f"[13] budget-starved solve: certified={starved.certified} after "
        f"{len(tr.rounds)} round(s), gap stalled at "
        f"{tr.final_gap:.2e} (> mip_gap 1e-05) — the round log shows "
        f"{tr.rounds[-1].nodes_live} node(s) still live when the budget "
        "ran out"
    )
    conv = {}
    full = halda_solve(
        devs, model, kv_bits="8bit", mip_gap=1e-5, backend="jax",
        convergence=conv,
    )
    tr = build_search_trace(conv)
    gaps = " -> ".join(
        f"{r.gap:.1e}" for r in tr.rounds if r.gap is not None
    )
    print(
        f"[13] full budget: certified={full.certified} in "
        f"{len(tr.rounds)} rounds / {tr.lp_iters_executed} LP iters "
        f"(gap {gaps})"
    )

    # ------------------------------------------------------------------
    # 14. Overload: everything so far replayed CLOSED-loop — the next
    #     event waits for the previous placement, so offered load can
    #     never exceed capacity. The traffic engine is open-loop: events
    #     fire at their scheduled time regardless of completion, and the
    #     gateway's admission control decides what happens when they pile
    #     up. Drive 4 small fleets 10x past saturation twice — once with
    #     only a bounded queue (sheds, each counted + flight-recorded +
    #     reconciled), once with coalescing (queued same-shard drift
    #     folds into single solves) — and read the plateau from the
    #     goodput, exactly the shape `make bench-compare` gates on the
    #     100-fleet trace (README "Overload & admission control").
    # ------------------------------------------------------------------
    from distilp_tpu.obs import FlightRecorder
    from distilp_tpu.traffic import (
        ArrivalConfig,
        generate_openloop_schedule,
        run_openloop,
    )

    ol_cfg = ArrivalConfig(
        seed=21, duration_s=40.0, base_rate=2.0, diurnal_amplitude=0.5,
        diurnal_period_s=40.0, n_regions=2, burst_rate_per_region=0.06,
        burst_factor=3.0, burst_duration_s=6.0, fleet_size=3, fleet_seed=42,
    )
    ol_specs, ol_items = generate_openloop_schedule(ol_cfg, 4)
    flight = FlightRecorder(capacity=2 * len(ol_items))
    shed_arm = run_openloop(
        gw_model, ol_specs, ol_items, 2, time_scale=0.001,
        k_candidates=[8, 10], max_queue_depth=2, flight=flight,
    )
    print(
        f"[14] open-loop flood, bounded queue (depth 2): "
        f"{shed_arm['offered']} offered @ ~{shed_arm['offered_eps']:.0f} "
        f"ev/s -> {shed_arm['served']} served, {shed_arm['shed']} shed "
        f"(reconciled: {not shed_arm['shed_violations']}), goodput "
        f"{shed_arm['goodput_eps']:.0f} ev/s"
    )
    co_arm = run_openloop(
        gw_model, ol_specs, ol_items, 2, time_scale=0.001,
        k_candidates=[8, 10], max_queue_depth=64, coalesce=True,
    )
    print(
        f"[14] same flood, coalescing: {co_arm['served']} served, "
        f"{co_arm['events_coalesced']} folded into "
        f"{co_arm['served'] - co_arm['events_coalesced']} solves, "
        f"0 shed, goodput {co_arm['goodput_eps']:.0f} ev/s, p99 "
        f"{co_arm['p99_ms']:.0f} ms — the burst compresses instead of "
        "queueing: saturation is a plateau, not a cliff"
    )

    # ------------------------------------------------------------------
    # 15. SLOs: step 14 showed the gateway SURVIVING overload; nothing
    #     yet said whether the run MET its objectives. Replay the same
    #     flood with an SLO attached: a timeline sampler snapshots the
    #     live metrics, the availability objective (1 - shed ratio,
    #     budget 1%) compiles into multi-window burn-rate rules, and the
    #     page-tier alert opens AT the shed onset (both windows burning
    #     >= 10x budget at once) and closes after recovery — hysteresis
    #     means flapping load could not flap it. The open/close trail
    #     lands in the counters AND the flight recorder, record for
    #     record (README "SLOs & alerting"; the same engine serves
    #     GET /slo and the /signals autoscaling payload under --listen).
    # ------------------------------------------------------------------
    from distilp_tpu.obs import SLOConfig

    slo_flight = FlightRecorder(capacity=2 * len(ol_items))
    slo_arm = run_openloop(
        gw_model, ol_specs, ol_items, 2, time_scale=0.001,
        k_candidates=[8, 10], max_queue_depth=2, flight=slo_flight,
        slo_config=SLOConfig.from_json("tests/traces/slo_live_spec.json"),
        settle_s=3.0,
    )
    slo = slo_arm["slo"]
    for e in slo["events"]:
        burns = ", ".join(f"{w}={b}x" for w, b in e["burn"].items())
        print(
            f"[15] alert {e['state']:<5s} {e['slo']}/{e['severity']} "
            f"(burn {burns})"
        )
    alert_recs = [
        r for r in slo_flight.snapshot("slo") if r.get("kind") == "slo_alert"
    ]
    print(
        f"[15] flood under an SLO: {slo_arm['shed']} shed -> "
        f"{slo['alerts_opened']} page opened at shed onset, "
        f"{slo['alerts_closed']} closed after recovery, "
        f"{len(alert_recs)} flight record(s) reconcile the trail "
        f"({slo['timeline_samples']} timeline samples)"
    )

    # ------------------------------------------------------------------
    # 16. Compile ledger: every second of XLA compile time above was
    #     invisible — a cold solve and a silently-recompiling one look
    #     identical from wall clock alone. Enable the process ledger and
    #     flip the LP engine pin mid-run: this process already compiled
    #     the ipm executables (step 12), so the ipm arm records ~zero
    #     compile events, while the pdhg arm mints new executables that
    #     the ledger attributes to the `lp_backend` STATIC-ARG FLIP —
    #     entry point, cause, and compile milliseconds, not an
    #     unexplained multi-second tick. `solver compiles` renders the
    #     same ledger from a live run or a dumped JSONL; `make
    #     smoke-compile` gates the zero-recompile warm-serving invariant
    #     (README "Compilation observability").
    # ------------------------------------------------------------------
    from distilp_tpu.obs import compile_ledger

    led = compile_ledger.enable()
    try:
        for engine in ("ipm", "pdhg"):
            tok = led.seq()
            sched = Scheduler(
                make_synthetic_fleet(4, seed=11), spec_model, mip_gap=1e-3,
                kv_bits="4bit", backend="jax", k_candidates=[8, 10],
                lp_backend=engine,
            )
            for ev in spec_events[:3]:
                sched.handle(ev)
            sched.close()
            evs = led.events_since(tok)
            causes = ",".join(sorted({e["cause"] for e in evs})) or "none"
            print(
                f"[16] lp_backend={engine}: {len(evs)} compile event(s) "
                f"({causes}), "
                f"{sum(e['compile_ms'] for e in evs):.0f} ms of XLA compile"
            )
        flips = [
            e for e in led.events_since(0)
            if e["cause"] == "static_arg_flip"
            and "lp_backend='pdhg'" in e["static"]
        ]
        print(
            f"[16] the engine flip minted {len(flips)} new executable(s), "
            f"attributed to {sorted({e['entry'] for e in flips})} — "
            "not an unexplained slow tick"
        )
    finally:
        compile_ledger.disable()

    # ------------------------------------------------------------------
    # 17. Memory ledger: the axis that decides how far any of this
    #     scales. First, the analytic model (ops/memmodel.py — the SAME
    #     formulas the bench's fleet_scale section skips arms on)
    #     diagnoses the IPM's M=4096 infeasibility WITHOUT running it:
    #     the factorizing engine's beam-batched (m, m) normal matrices
    #     are ~14.5 GB at M=4096 — nearly 2x the 8 GB HBM-class cap,
    #     and that analytic figure is a LOWER bound (the bench's memory
    #     section measures XLA temp bytes at ~7-8x the proxy for the
    #     full IPM executable); the matrix-free PDHG's one (m, n)
    #     operator is ~1.2 GB. Then the live half: enable the
    #     ledger and watch live-array bytes across cold -> warm -> spec
    #     ticks — provisioning happens at the cold tick, and the warm
    #     path stays FLAT (the zero-leak gate `make smoke-memory` and
    #     the bench pin absolutely; README "Memory observability").
    # ------------------------------------------------------------------
    from distilp_tpu.obs import memory as obs_memory
    from distilp_tpu.ops import memmodel

    M_big2 = 4096
    print(
        f"[17] analytic model at M={M_big2}: ipm needs "
        f"~{memmodel.peak_gb(M_big2, 'ipm'):.0f} GB (beam-batched normal "
        f"matrices), pdhg ~{memmodel.peak_gb(M_big2, 'pdhg'):.1f} GB "
        "(one matrix-free operator)"
    )
    print(
        f"[17] fleet_scale's skip verdict, without solving: ipm is "
        f"{memmodel.ipm_memory_infeasible(M_big2, 8.0)}"
    )

    led = obs_memory.enable(
        obs_memory.MemoryLedger(sample_min_interval_s=0.0)
    )
    try:
        sched = Scheduler(
            make_synthetic_fleet(4, seed=11), spec_model, mip_gap=1e-3,
            kv_bits="4bit", backend="jax", k_candidates=[8, 10],
            speculative=True,
        )
        marks = []
        for i, ev in enumerate(spec_events[:16]):
            view = sched.handle(ev)
            rec = led.sample(force=True)
            marks.append((view.mode, rec["live_bytes"]))
            if i == 4:
                led.mark_warm()  # cold + warm layouts + scenario batch in
        first_spec = next(
            (i for i, (m, _) in enumerate(marks) if m == "spec"), None
        )
        spec_bytes = marks[
            first_spec if first_spec is not None else -1
        ][1]
        print(
            f"[17] live-array bytes: cold tick {marks[0][1]} B -> "
            f"warm tick {marks[2][1]} B -> spec tick {spec_bytes} B "
            f"(modes: {' '.join(m for m, _ in marks[:8])} ...)"
        )
        leak = led.leak_report()
        entry = led.analyses.get("solver._solve_packed", {})
        mem = entry.get("memory") or {}
        flops = entry.get("flops")
        growth = f"{leak['growth_bytes']:+d} B" if leak else "n/a"
        print(
            f"[17] leak gate across the warm/spec phase: "
            f"{'FLAT' if leak and leak['flat'] else 'GREW'} "
            f"({growth}); static model for solver._solve_packed: "
            f"temp={(mem.get('temp_bytes') or 0) / 1e6:.2f} MB, "
            f"flops={f'{flops:.3g}' if flops is not None else 'n/a'}"
            f"/dispatch; headroom "
            f"{(led.headroom_bytes() or 0) / 1e9:.1f} GB"
        )
        sched.close()
    finally:
        obs_memory.disable()

    # ------------------------------------------------------------------
    # 18. Cross-shard batched solving: step 14's coalescer compresses a
    #     flood WITHIN each shard; a 100-fleet flood still pays one
    #     dispatch per fleet. Flip admission into combine mode and the
    #     gateway packs pending ticks from MANY fleets into one padded
    #     device batch behind the coalescer — one `_solve_batched`
    #     dispatch per bucket flush, every lane decoded back to its own
    #     shard with its own certificate. The bucket policy is COMMITTED
    #     (padded-M boundaries x power-of-two lane counts), and
    #     `warm_combine()` traces the whole reachable executable set at
    #     the warm boundary, so the measured phase compiles nothing: the
    #     compile ledger shows one executable set per bucket, minted
    #     before the first combined tick (README "Cross-shard batched
    #     solving").
    # ------------------------------------------------------------------
    flood_cfg = ArrivalConfig(
        seed=33, duration_s=8.0, base_rate=25.0, n_regions=4,
        burst_rate_per_region=0.05, burst_factor=3.0, burst_duration_s=2.0,
        fleet_size=3, fleet_seed=77,
    )
    flood_specs, flood_items = generate_openloop_schedule(flood_cfg, 100)
    led = compile_ledger.enable()
    try:
        per_shard = run_openloop(
            gw_model, flood_specs, flood_items, 2, time_scale=0.002,
            k_candidates=[8, 10], max_queue_depth=256, coalesce=True,
        )
        combined = run_openloop(
            gw_model, flood_specs, flood_items, 2, time_scale=0.002,
            k_candidates=[8, 10], max_queue_depth=256, coalesce=True,
            combine=True,
        )
    finally:
        compile_ledger.disable()
    print(
        f"[18] 100-fleet flood, per-shard: {per_shard['served']} served, "
        f"goodput {per_shard['goodput_eps']:.0f} ev/s, p99 "
        f"{per_shard['p99_ms']:.0f} ms"
    )
    comb = combined["combine"]
    print(
        f"[18] same flood, combined: {combined['served']} served, "
        f"goodput {combined['goodput_eps']:.0f} ev/s, p99 "
        f"{combined['p99_ms']:.0f} ms — {comb['instances']} lanes in "
        f"{comb['batches']} batched dispatches (occupancy "
        f"{comb['occupancy_mean'] or 0:.1f}, padding waste "
        f"{comb['padding_waste_mean'] or 0:.2f}), "
        f"{comb['combine_local']} local, {comb['combine_fallback']} "
        "fallbacks"
    )
    wp = combined["compile"]["warm_phase_events"]
    wp_entries = sorted(
        {str(e) for e in combined["compile"].get("warm_phase_entries") or []}
    )
    if wp == 0:
        verdict = (
            "— batching across shards minted NOTHING the warmup had not "
            "already traced"
        )
    elif not any("_solve_batched" in e for e in wp_entries):
        # The bucket contract held (no _solve_batched executable escaped
        # warm_combine); the events are per-shard escalations — an
        # uncertified lane falls back to a local re-solve with escalated
        # search parameters, the same executable an uncertified PER-SHARD
        # tick would mint. The ledger attributes them by entry point.
        verdict = (
            f"(attributed: {', '.join(wp_entries)}) — no bucket executable "
            "escaped warm_combine; these are uncertified-lane fallbacks "
            "re-solving locally with escalated search parameters"
        )
    else:
        verdict = (
            f"(attributed: {', '.join(wp_entries)}) — a bucket or lane "
            "shape ESCAPED the committed policy; see warm_phase_entries"
        )
    print(
        f"[18] compile ledger: {comb['warmup']['buckets']} bucket(s), "
        f"{comb['warmup']['shapes_traced']} shapes traced at the warm "
        f"boundary, {wp} compile event(s) in the measured phase {verdict}"
    )

    # ------------------------------------------------------------------
    # 19. Fleet-scale sharded solving: everything above fit one device.
    #     An M=512 fleet's HALDA relaxation does not stay that polite —
    #     the dense (m, n) operator plus per-node iterate vectors is what
    #     caps the fleet sizes one accelerator can price. ops/meshlp.py
    #     row-partitions the PDHG solve across a device mesh (4 virtual
    #     host devices here, forced at the top of main): each shard holds
    #     a (B, m/4, n) row block and meets the others only at psum/pmax/
    #     all_gather reduction points, so per-device memory drops ~4x
    #     while the math computes the SAME iteration. Iterates run in
    #     f32; the certificate is still the f64 Lagrangian bound from the
    #     final duals — precision moves bound tightness, never validity
    #     (README "Fleet-scale sharded solving"). The convergence
    #     telemetry from step [16]'s machinery rides the sharded solve
    #     unchanged: restart cadence and iters-to-certify come from the
    #     same decoded in-dispatch trace.
    # ------------------------------------------------------------------
    import jax

    from distilp_tpu.common import load_model_profile
    from distilp_tpu.obs.convergence import build_search_trace
    from distilp_tpu.ops import memmodel
    from distilp_tpu.utils import stretch_model_for_fleet

    fleet_m = 512
    shards = 4 if len(jax.devices()) >= 4 else 1
    big_model = stretch_model_for_fleet(
        load_model_profile(
            str(REPO / "tests" / "profiles" / "llama_3_70b" / "online"
                / "model_profile.json")
        ),
        fleet_m,
    )
    big_fleet = make_synthetic_fleet(fleet_m, seed=123)
    conv: dict = {}
    tm: dict = {}
    big = halda_solve(
        big_fleet, big_model, kv_bits="4bit", mip_gap=0.05, backend="jax",
        lp_backend="pdhg", mesh_shards=shards, pdhg_dtype="f32",
        timings=tm, convergence=conv,
    )
    per_shard_mb = memmodel.pdhg_shard_peak_bytes(
        fleet_m, shards, memmodel.dtype_bytes_of("f32")
    ) / 1e6
    print(
        f"[19] M={fleet_m} fleet, {shards}-shard row mesh, f32 iterates: "
        f"k={big.k} obj={big.obj_value:.4f} certified={big.certified} "
        f"(f64 gap {big.gap:.2e}) in {tm.get('solve_ms', 0.0):.0f} ms — "
        f"~{per_shard_mb:.0f} MB modeled working set per shard "
        f"(mesh_shards={tm.get('mesh_shards')})"
    )
    trace = build_search_trace(conv)
    final_gap = (
        f"{trace.final_gap:.2e}" if trace.final_gap is not None else "n/a"
    )
    print(
        f"[19] convergence trace over the mesh: {len(trace.rounds)} "
        f"round(s), {trace.restarts} Halpern restart(s), "
        f"{trace.lp_iters_executed} LP iterations "
        f"({trace.iters_to_certify} to certify), final gap {final_gap}"
    )

    # ------------------------------------------------------------------
    # 20. Close the loop: step 15 WATCHED the flood page; now the page
    #     STEERS the fleet. Same flood shape, 10 fleets, one PROCESS
    #     worker (schedulers live in a subprocess behind the unix-socket
    #     RPC — the stub factory keeps the children jax-free and this
    #     step inside the walkthrough's minute budget; the bench
    #     federation section runs the real scheduler in children). A
    #     ControlLoop reads the same /signals payload the HTTP surface
    #     serves, a post-warmup closed-loop probe fills the headroom
    #     denominator, and the committed policy does the rest: the page
    #     alert votes, the controller flips forced-degrade ON and spawns
    #     worker 1, the ring rebalance migrates shards into the fresh
    #     subprocess WARM (zero cold ticks), and once the burst drains
    #     the alert clears and degrade lifts. Every decision is counted
    #     AND flight-recorded with the signals snapshot that justified
    #     it — the `violations` reconciliation (trail vs counters vs
    #     actuations) is the same audit `make smoke-autoscale` gates,
    #     and `solver autoscale` replays the dumped timeline through the
    #     same Controller byte-for-byte offline (README "Closed-loop
    #     autoscaling & process workers").
    # ------------------------------------------------------------------
    from distilp_tpu.control import ControlPolicy

    as_cfg = ArrivalConfig(
        seed=21, duration_s=40.0, base_rate=4.0, diurnal_amplitude=0.5,
        diurnal_period_s=40.0, n_regions=2, burst_rate_per_region=0.08,
        burst_factor=6.0, burst_duration_s=8.0, fleet_size=3, fleet_seed=42,
    )
    as_specs, as_items = generate_openloop_schedule(as_cfg, 10)
    ctl_flight = FlightRecorder(capacity=2 * len(as_items))
    as_arm = run_openloop(
        "stub", as_specs, as_items, 1, time_scale=0.001,
        max_queue_depth=2, flight=ctl_flight,
        slo_config=SLOConfig.from_json("tests/traces/slo_live_spec.json"),
        worker_backend="process",
        scheduler_factory="tests.procstub:make_scheduler",
        autoscale=ControlPolicy.from_json(
            "tests/traces/control_live_policy.json"
        ),
        capacity_probe_events=3, control_period_s=0.05, settle_s=3.0,
    )
    ctl = as_arm["control"]
    for a in ctl["actions"]:
        extra = (
            f" -> {a['target_workers']} workers"
            if a.get("target_workers") is not None else ""
        )
        print(f"[20] {a['kind']:<11s}{extra}  ({a['reason']})")
    cc = ctl["counters"]
    print(
        f"[20] closed loop on process workers: {as_arm['shed']} shed "
        f"paged the SLO, {cc.get('control_scale_out', 0)} scale-out "
        f"spawned worker(s) (final fleet {ctl['workers_final']}), "
        f"{cc.get('shards_migrated', 0)} shard(s) migrated live, "
        f"capacity probe {ctl['capacity_eps']:.0f} ev/s; "
        f"{len(ctl_flight.snapshot('control'))} flight record(s) "
        f"reconcile the trail (violations: {ctl['violations'] or 'none'})"
    )

    # ------------------------------------------------------------------
    # 21. Crash tolerance: step 20's subprocess children are now allowed
    #     to DIE. A supervised process gateway journals every accepted
    #     event into a per-fleet WAL before its RPC dispatches and takes
    #     bit-exact micro-snapshots every few events; here we SIGKILL
    #     the child twice mid-stream and let the supervisor do its job —
    #     detect the dead socket, respawn with backoff, restore the last
    #     snapshot WARM and replay only the WAL tail. The interrupted
    #     event is applied exactly once (seq never gaps, never repeats),
    #     and the whole incident is narrated from the flight recorder's
    #     `recovery` ring: every kill's recovery is reconstructible from
    #     the trail alone (README "Crash recovery & supervision";
    #     `make smoke-crash` runs the same contract on the real
    #     scheduler through a committed fault plan).
    # ------------------------------------------------------------------
    import shutil as _shutil
    import tempfile as _tempfile

    rec_dir = _tempfile.mkdtemp(prefix="distilp-recovery-")
    rec_flight = FlightRecorder(capacity=256)
    rgw = Gateway(
        n_workers=1, scheduler_factory="tests.procstub:make_scheduler",
        worker_backend="process", supervise=True, recovery_dir=rec_dir,
        snapshot_every=4, flight=rec_flight,
        backoff_base_s=0.01, backoff_max_s=0.05,
    )
    try:
        for i in range(3):
            fid = f"c{i:02d}"
            rgw.register_fleet(
                fid, make_fleet_from_spec(fid, {"m": 3, "seed": 210 + i}),
                "stub",
            )
        crash_fleets = sorted(rgw._fleet_key)
        seqs = {fid: 0 for fid in crash_fleets}
        for step in range(8):
            if step in (3, 6):  # SIGKILL mid-stream, twice
                rgw.workers[0].kill_child()
            for fid in crash_fleets:
                seqs[fid] = rgw.handle_event(fid, f"flood{step}")["seq"]
        assert all(s == 8 for s in seqs.values()), seqs
        rec = rgw.recovery_status()
        for r in rec_flight.snapshot("recovery"):
            print(
                f"[21] {r['action']:<9s} worker {r['worker']} "
                f"gen {r['generation']} in {r['mttr_ms']:.0f} ms "
                f"({len(r['fleets'])} shard(s), "
                f"{r['crashes_in_window']} crash(es) in window)"
            )
        print(
            f"[21] {rec['worker_crashes']} kill(s) -> "
            f"{rec['child_respawns']} respawn(s): "
            f"{rec['events_replayed']} WAL record(s) replayed over "
            f"{rec['micro_snapshots']} micro-snapshot(s), "
            f"{rec['warm_resumes']} warm resume(s), "
            f"events_lost={rec['events_lost']} (exactly-once), "
            f"every fleet at seq 8 with no gap and no repeat"
        )
    finally:
        rgw.close()
        _shutil.rmtree(rec_dir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

# dlint (tools/dlint/) is the stdlib-only correctness gate and runs
# everywhere; ruff stays authoritative for style wherever it is installed.
.PHONY: lint
lint:
	python -m tools.dlint
	@if command -v ruff >/dev/null 2>&1; then ruff check .; fi

# Strict gate for CI and the tier-1 path: any non-baselined finding fails,
# and the baseline itself must be empty or justified (no stale entries, a
# reason on every entry). See README "Static analysis gate".
.PHONY: lint-strict
lint-strict:
	python -m tools.dlint --strict
	@if command -v ruff >/dev/null 2>&1; then ruff check .; fi

# The whole-program concurrency pass alone (DLP030-034): guarded-by
# discipline, blocking-under-lock, lock-order cycles, asyncio hazards and
# thread-escapes, over the static lock/call model. Subset of lint-strict;
# exists as the fast dev loop while editing locking code.
.PHONY: lint-concurrency
lint-concurrency:
	python -m tools.dlint --strict --select DLP030,DLP031,DLP032,DLP033,DLP034

.PHONY: format
format:
	ruff format --diff .

.PHONY: test
test: lint-strict smoke-twin smoke-chaos smoke-gateway smoke-spec smoke-diag smoke-overload smoke-slo smoke-compile smoke-memory smoke-combine smoke-lockwatch smoke-shard smoke-autoscale smoke-crash
	python -m pytest tests/ -q

# Lock-sanitizer smoke: the runtime half of DLP032's deadlock claim. The
# overload COALESCE arm (saturating flood folded into batches) replays
# with DLP_LOCKWATCH=1, so every make_lock() primitive records per-thread
# acquisition ORDER; batch admission is the serving loop's one guaranteed
# nesting (worker.submit's bound check runs inside the admission lock so
# depth accounting and batch state move atomically), so the observed
# graph is non-empty by construction. Then `dlint --check-lockwatch`
# cross-validates: observed edges must be a subset of the static
# acquisition graph (the model missed nothing that actually happens),
# and zero cycle witnesses may have fired. This is what keeps the static
# DLP032 graph honest — a refactor that nests locks in an order the
# analyzer cannot see fails HERE, not in prod.
.PHONY: smoke-lockwatch
smoke-lockwatch: lint-strict
	@D=$$(mktemp -d) && \
	JAX_PLATFORMS=cpu DLP_LOCKWATCH=1 DLP_LOCKWATCH_OUT=$$D/lockwatch.json \
	python -m distilp_tpu.cli.solver_cli overload \
		--trace tests/traces/openloop_diurnal_burst.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--workers 2 --k-candidates 8,10 --time-scale 0.001 \
		--max-queue-depth 64 --coalesce --check --expect-coalesced \
		--expect-no-sheds --quiet && \
	python -m tools.dlint --check-lockwatch $$D/lockwatch.json; \
	rc=$$?; rm -rf $$D; exit $$rc

# `make bench` also appends the run's headline keys as one line of
# BENCH_HISTORY.jsonl (committed format, see tools/bench_history.py) so
# the bench trajectory stays machine-readable; trend-check it with
# `solver slo --history BENCH_HISTORY.jsonl`.
.PHONY: bench
bench:
	python bench.py --history BENCH_HISTORY.jsonl

# Regression gate for the perf dev loop: run the bench and diff every
# headline metric against a committed capture (default: the latest
# BENCH_rNN.json). Exits nonzero on a >20% regression of `value` (cold
# sweep ms) or `warm_tick_ms` (streaming fast path). The gate compares
# ABSOLUTE milliseconds, so the reference must come from the same box —
# when tiny_put_ms (the recorded per-op dispatch floor) differs >1.5x the
# compare prints a not-comparable warning; re-capture a local reference
# (`python bench.py > /tmp/ref.json`) before trusting the verdict. Usage:
#   make bench-compare                      # vs $(AGAINST)
#   make bench-compare AGAINST=BENCH_r04.json
AGAINST ?= BENCH_r05.json
.PHONY: bench-compare
bench-compare:
	python bench.py --against $(AGAINST)

# Digital-twin smoke: a seeded 256-sample Monte-Carlo robustness report on
# a bundled golden fixture, on the CPU platform. --check-determinism runs
# the vmapped report twice with the same seed and fails on any difference;
# --json output is piped through a schema re-validation, and the command's
# own exit gate asserts the twin's unperturbed latency matches the HALDA
# objective (the conformance cross-check). Chained into `make test`.
# && chain to a per-invocation temp file, NOT a pipeline: /bin/sh has no
# pipefail, and the evaluate CLI prints its JSON before the cross-check
# exit gate — piped, a failing gate would be masked by the downstream
# validator's success; a fixed path would race concurrent runs.
.PHONY: smoke-twin
smoke-twin: lint-strict
	@T=$$(mktemp) && \
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli evaluate \
		--profile tests/profiles/llama_3_70b/online \
		--samples 256 --seed 7 --dropout-p 0.05 \
		--check-determinism --json > $$T && \
	JAX_PLATFORMS=cpu python -c "import json; \
		from distilp_tpu.twin import RobustnessReport, TwinEvaluation; \
		d=json.load(open('$$T')); \
		TwinEvaluation.model_validate(d['evaluation']); \
		RobustnessReport.model_validate(d['robustness']); \
		print('smoke-twin OK: report schema + determinism + objective cross-check')"; \
	rc=$$?; rm -f $$T; exit $$rc

# Scheduler-service smoke: replay the bundled 20-event churn trace through
# the daemon on the CPU platform (no slow tests, no accelerator needed);
# any structural tick missing its optimality certificate fails the target.
# Chained behind lint-strict so the smoke path can't drift from the gate.
# Chaos soak: the bundled churn trace replayed under a seeded fault plan
# (solver exceptions incl. a breaker-opening consecutive pair, a latency
# spike, NaN-poisoned and malformed events, a device-dropout burst) with
# the hardened serving knobs on. --chaos-check exits 1 unless every tick
# served a structurally valid placement, every poisoned/malformed event
# was quarantined and accounted in the counters, and the service returned
# to 'healthy' within the recovery budget. The deadline is deliberately
# generous (the point here is exercising the worker-thread solve path,
# not winning a race against this box's compile times); tight-deadline
# misses are pinned deterministically in tests/test_faults.py.
.PHONY: smoke-chaos
smoke-chaos: lint-strict
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli serve \
		--trace tests/traces/scheduler_smoke_20.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--synthetic-fleet 4 --fleet-seed 11 --k-candidates 8,10 \
		--fault-plan tests/traces/chaos_plan.json \
		--deadline-ms 60000 --max-retries 2 --breaker-threshold 2 \
		--chaos-check --quiet
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli serve \
		--trace tests/traces/scheduler_smoke_20.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--synthetic-fleet 4 --fleet-seed 11 --k-candidates 8,10 \
		--fault-plan tests/traces/chaos_plan.json \
		--deadline-ms 60000 --max-retries 2 --breaker-threshold 2 \
		--chaos-check --quiet --speculate

# Speculative-replanning smoke: the bundled burst trace (correlated
# multi-device spikes that relax exactly) replayed with --speculate on the
# same 4-device fleet the chaos smoke uses. The soak contract here:
# speculative hits actually happened (hit_rate > 0 over the whole trace,
# cold-bank warmup included), every probe is accounted (hits + misses ==
# probed ticks, hits never exceed what was banked), no tick failed, and no
# structural tick missed its certificate (--fail-uncertified). The chaos
# interaction — spec counters reconciling under injected faults — is the
# second smoke-chaos invocation above; the p99 speculation-on-vs-off gate
# lives in the bench (`speculation` section, `make bench-compare`).
.PHONY: smoke-spec
smoke-spec: lint-strict
	@T=$$(mktemp) && \
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli serve \
		--trace tests/traces/spec_burst.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--synthetic-fleet 4 --fleet-seed 11 --k-candidates 8,10 \
		--speculate --quiet --fail-uncertified --metrics-out $$T && \
	JAX_PLATFORMS=cpu python -c "import json; \
		s=json.load(open('$$T')); sp=s['speculation']; r=s['replay']; \
		assert sp['hits'] > 0, 'no speculative hits on the burst trace'; \
		assert sp['hit_rate'] > 0, 'zero hit rate'; \
		assert sp['hits'] + sp['misses'] <= r['events'], 'probe accounting'; \
		assert r['failed_ticks'] == 0, 'failed ticks under speculation'; \
		assert r['structural_uncertified'] == 0, 'uncertified structural tick'; \
		print('smoke-spec OK: %d/%d ticks served from the bank (hit rate %.0f%%), 0 failures' \
			% (sp['hits'], sp['hits'] + sp['misses'], 100 * sp['hit_rate']))"; \
	rc=$$?; rm -f $$T; exit $$rc

# Gateway smoke: the zero-downtime drain/restore contract, end to end.
# Three serve runs over the bundled 10-fleet trace through 2 sharded
# workers: (1) uninterrupted reference; (2) snapshot after 15 events then
# HALT (the "kill" half — warm state on disk, process gone); (3) --resume
# from the snapshot, replaying only the uncovered suffix. The comparator
# asserts the resumed run's final placements are IDENTICAL to the
# uninterrupted run's, that every restored shard's first tick rode warm
# (warm_resumes == shards touched, cold_resumes == 0) and that the
# resumed run paid ZERO cold solves. Then the chaos soak of smoke-chaos
# runs unchanged against the multi-worker path (--workers 2): the soak
# contract (valid placement every tick, quarantine accounting, bounded
# recovery) must hold identically when the scheduler lives on a shard
# worker — per-shard HealthState isolation is pinned in tests/test_gateway.py.
.PHONY: smoke-gateway
smoke-gateway: lint-strict
	@D=$$(mktemp -d) && \
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli serve \
		--trace tests/traces/gateway_smoke_10f.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--workers 2 --k-candidates 8,10 --quiet --fail-uncertified \
		--metrics-out $$D/full.json && \
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli serve \
		--trace tests/traces/gateway_smoke_10f.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--workers 2 --k-candidates 8,10 --quiet \
		--snapshot-dir $$D/snap --snapshot-at 15 --halt-after-snapshot && \
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli serve \
		--trace tests/traces/gateway_smoke_10f.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--workers 2 --k-candidates 8,10 --quiet --fail-uncertified \
		--snapshot-dir $$D/snap --resume --metrics-out $$D/resumed.json && \
	JAX_PLATFORMS=cpu python -c "import json; \
		full=json.load(open('$$D/full.json')); \
		res=json.load(open('$$D/resumed.json')); \
		assert res['final_placements']==full['final_placements'], 'restored placements diverged'; \
		g=res['gateway']; \
		assert g['warm_resumes']>0, 'no warm resumes'; \
		assert g['cold_resumes']==0 and g['tick_cold']==0, 'cold re-solve after restore'; \
		print('smoke-gateway OK: %d shards resumed warm, placements identical, 0 cold re-solves' % g['warm_resumes'])" && \
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli serve \
		--trace tests/traces/scheduler_smoke_20.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--synthetic-fleet 4 --fleet-seed 11 --k-candidates 8,10 \
		--fault-plan tests/traces/chaos_plan.json \
		--deadline-ms 60000 --max-retries 2 --breaker-threshold 2 \
		--chaos-check --quiet --workers 2; \
	rc=$$?; rm -rf $$D; exit $$rc

# Convergence-diagnostics smoke: the 16-device north star solved with
# solver-interior telemetry on (`solver diagnose`), per LP engine. The gate
# asserts the report is NON-EMPTY with a certified final gap at mip_gap and
# that the accounting is exact: the per-round LP iteration counts sum to
# the ipm_iters_executed header counter, and the per-round gap trajectory
# is monotone non-increasing (incumbent only improves, bound only rises).
# Chained into `make test` so the diagnose path can't silently rot.
.PHONY: smoke-diag
smoke-diag: lint-strict
	@T=$$(mktemp) && rc=0; \
	for eng in ipm pdhg; do \
		JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli diagnose \
			--profile tests/profiles/llama_3_70b/online \
			--synthetic-fleet 16 --fleet-seed 123 --mip-gap 1e-3 \
			--lp-backend $$eng --json > $$T && \
		JAX_PLATFORMS=cpu python -c "import json, sys; \
			d = json.load(open('$$T')); eng = '$$eng'; \
			assert d['rounds'], 'empty diagnose report'; \
			assert d['lp_backend'] == eng, d['lp_backend']; \
			assert d['certified'], 'north star not certified under ' + eng; \
			assert d['final_gap'] is not None and d['final_gap'] <= 1e-3 + 1e-12; \
			gaps = [r['gap'] for r in d['rounds'] if r['gap'] is not None]; \
			assert all(a >= b - 1e-12 for a, b in zip(gaps, gaps[1:])), gaps; \
			total = sum(r['lp_iters'] for r in d['rounds']); \
			assert total == d['lp_iters_executed'], (total, d['lp_iters_executed']); \
			print('smoke-diag OK [%s]: %d rounds, %d LP iters, %d restarts, gap %.2e' \
				% (eng, len(d['rounds']), total, d['restarts'], d['final_gap']))" \
		|| { rc=1; break; }; \
	done; rm -f $$T; exit $$rc

# Overload smoke: the committed diurnal+burst open-loop capture replayed
# at time-scale 0.001 — the whole 60 s schedule fires in ~60 ms, a
# deterministic saturating flood (~190 events vs 2 workers). Two arms:
# (1) a tiny bounded queue with NO coalescing must SHED, and --check
# reconciles every shed record-by-record against the flight recorder
# (counter == per-fleet monotone shed indices, parseable Retry-After on
# every record) while every served placement stays structurally valid;
# (2) the same flood with coalescing on must FOLD queued same-shard drift
# into single solves (events_coalesced > 0) and serve everything without
# shedding a deep queue. The graceful-saturation plateau gate (10x
# sustainable on the 100-fleet trace) is the bench's job (`overload`
# section, `make bench-compare`); this smoke pins the admission
# machinery's accounting contract.
.PHONY: smoke-overload
smoke-overload: lint-strict
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli overload \
		--trace tests/traces/openloop_diurnal_burst.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--workers 2 --k-candidates 8,10 --time-scale 0.001 \
		--max-queue-depth 2 --check --expect-sheds --quiet
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli overload \
		--trace tests/traces/openloop_diurnal_burst.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--workers 2 --k-candidates 8,10 --time-scale 0.001 \
		--max-queue-depth 64 --coalesce --check --expect-coalesced \
		--expect-no-sheds --quiet

# SLO smoke: burn-rate alerting, both halves of the determinism claim.
# (1) OFFLINE: the committed synthetic overload timeline (regeneration
# pinned byte-exact in tests/test_slo.py) replayed against the committed
# spec must reproduce the committed expected alert sequence EXACTLY —
# tier, window set, state and firing-timestamp bucket; evaluation over a
# dumped timeline is a pure function of (timeline, spec, step), so any
# diff is evaluator drift, not noise. --check also reconciles the
# transition list against the engine's own counters and flight records.
# (2) LIVE: the committed diurnal+burst capture replayed as the
# smoke-overload flood (time-scale 0.001, depth-2 queue -> ~90% shed)
# with the SLO engine sampling live: the availability page alert must
# OPEN at the shed onset and CLOSE during the settle window, reconciled
# record-by-record (engine events == counters == flight records), on top
# of the usual shed-accounting contract. The sampler-overhead <= 5% gate
# is the bench's job (`slo` section, `make bench-compare`).
.PHONY: smoke-slo
smoke-slo: lint-strict
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli slo \
		--spec tests/traces/slo_overload_spec.json \
		--timeline tests/traces/slo_timeline_overload.jsonl \
		--step-s 0.1 --expect tests/traces/slo_expected_alerts.json \
		--check --quiet
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli overload \
		--trace tests/traces/openloop_diurnal_burst.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--workers 2 --k-candidates 8,10 --time-scale 0.001 \
		--max-queue-depth 2 --check --expect-sheds \
		--slo tests/traces/slo_live_spec.json --settle-s 3 \
		--expect-alert page --quiet

# Autoscale smoke: the closed control loop, both halves of its
# determinism claim (mirrors smoke-slo's offline/live split).
# (1) OFFLINE: Controller.replay over the committed synthetic overload
# timeline + committed policy must reproduce the committed action
# fixture BYTE-for-byte — decisions over a dumped timeline are a pure
# function of (timeline, policy, spec, step), so any diff is controller
# drift, not noise; --check replays twice and fails on any difference.
# (2) LIVE: the committed diurnal+burst capture replayed as a
# time-scaled flood through ONE process-backed worker (stub factory —
# the child hosts schedulers behind the unix-socket RPC, no jax) with a
# tiny queue and the live SLO spec: sheds open the availability page
# alert, the controller votes scale_out on it, a second worker
# subprocess spawns and the ring rebalance migrates shards live. The
# --check contract reconciles actions == counters == flight records,
# spawn/retire counts against scale actions, zero failed migrations,
# and --expect-scale 2 asserts the fleet actually reached two workers.
.PHONY: smoke-autoscale
smoke-autoscale: lint-strict
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli autoscale \
		--timeline tests/traces/slo_timeline_overload.jsonl \
		--policy tests/traces/control_policy.json \
		--spec tests/traces/slo_overload_spec.json \
		--step-s 0.5 --expect tests/traces/control_expected_actions.jsonl \
		--check --quiet
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli overload \
		--trace tests/traces/openloop_diurnal_burst.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--workers 1 --k-candidates 8,10 --time-scale 0.001 \
		--max-queue-depth 2 \
		--worker-backend process \
		--scheduler-factory tests.procstub:make_scheduler \
		--autoscale tests/traces/control_live_policy.json \
		--slo tests/traces/slo_live_spec.json \
		--capacity-probe 3 --control-period-s 0.05 \
		--check --expect-scale 2 --expect-sheds --expect-alert page \
		--settle-s 3 --quiet

# Crash-tolerance smoke: the chaos trace served by a SUPERVISED
# process-backed worker whose child eats two kill -9s mid-soak (plus a
# one-shot rpc_delay for the degraded-but-alive corner) — each kill
# exercises the whole recovery chain inline: crash detection on the dead
# socket, respawn with backoff, micro-snapshot restore, WAL-tail replay,
# then the interrupted dispatch re-serves. `--chaos-check` fails the run
# unless the crash contract holds: events_lost == 0 (WAL lost nothing,
# replay double-applied nothing), zero cold resumes (every shard came
# back warm from its snapshot), every crash answered by a respawn or a
# quarantine, and the soak returns to healthy. Run on BOTH LP engines —
# dump/load bit-exactness is per engine, so warm recovery must be proven
# per engine. Torn-frame/EOF taxonomy and the crash-loop breaker are
# pytest's half (tests/test_procworker.py, tests/test_recovery.py).
.PHONY: smoke-crash
smoke-crash: lint-strict
	@for eng in ipm pdhg; do \
		D=$$(mktemp -d) ; \
		JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli serve \
			--trace tests/traces/scheduler_smoke_20.jsonl \
			--profile tests/profiles/llama_3_70b/online \
			--synthetic-fleet 4 --fleet-seed 11 --k-candidates 8,10 \
			--lp-backend $$eng \
			--worker-backend process --supervise \
			--recovery-dir $$D --snapshot-every 4 \
			--fault-plan tests/traces/crash_plan.json \
			--chaos-check --quiet ; \
		rc=$$? ; rm -rf $$D ; \
		[ $$rc -eq 0 ] || exit $$rc ; \
	done

# Combine smoke: the committed diurnal+burst capture replayed with
# cross-shard batching ON (coalesce folds a shard's burst into one tick;
# combine packs pending ticks from MANY shards into padded device
# batches solved by one _solve_batched dispatch per bucket flush). The
# contract (--expect-combined): combined batches actually served lanes,
# ZERO ticks fell back to a per-shard solve, zero batched dispatches
# raised, and — the committed-bucket-policy invariant — the measured
# phase compiled NOTHING (warm_phase_events == 0: warm_combine traced
# the whole reachable executable set, padded-M boundaries x quantized
# lane counts x the root-warm signature flip, at the warm boundary).
# Every served placement is structurally valid and nothing sheds.
.PHONY: smoke-combine
smoke-combine: lint-strict
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli overload \
		--trace tests/traces/openloop_diurnal_burst.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--workers 2 --k-candidates 8,10 --time-scale 0.001 \
		--max-queue-depth 64 --coalesce --combine \
		--check --expect-combined --expect-no-sheds --quiet

# Compile-ledger smoke: the bundled 10-fleet gateway trace replayed with
# the XLA compile ledger on (serve --compile-ledger-out). The contract:
# (1) cold compiles happened and EVERY one is attributed to a registered
# entry point (no "(unregistered)" executables — the surface DLP020
# guards statically, checked dynamically here); (2) after every fleet's
# 2-event warmup, the steady-state warm serving phase recorded ZERO
# compile events (the zero-recompile invariant the bench gates as
# compile_warm_phase_count == 0); (3) no exact-signature recompile ever
# (each distinct static+shape signature compiles at most once); (4) the
# dumped ledger JSONL round-trips byte-stably and `solver compiles`
# renders byte-identical reports on repeated replays of the same dump.
.PHONY: smoke-compile
smoke-compile: lint-strict
	@D=$$(mktemp -d) && \
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli serve \
		--trace tests/traces/gateway_smoke_10f.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--workers 2 --k-candidates 8,10 --quiet \
		--compile-ledger-out $$D/ledger.jsonl --metrics-out $$D/m.json \
		> /dev/null && \
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli compiles \
		--load $$D/ledger.jsonl --check > $$D/report1.txt && \
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli compiles \
		--load $$D/ledger.jsonl --check > $$D/report2.txt && \
	cmp -s $$D/report1.txt $$D/report2.txt && \
	JAX_PLATFORMS=cpu python -c "import json; \
		m = json.load(open('$$D/m.json')); c = m['compile']; \
		assert c['warm_boundary_marked'], 'warm boundary never marked'; \
		assert c['warm_phase_compiles'] == 0, ('warm phase recompiled', c['warm_phase_compiles']); \
		assert c['counters']['compiles'] > 0, 'no cold compiles recorded'; \
		assert c['unregistered_compiles'] == 0, 'unregistered compile event'; \
		compiled = [n for n, e in c['entries'].items() if e['compiles']]; \
		assert set(compiled) <= set(c['registered']), compiled; \
		print('smoke-compile OK: %d cold compile(s) across %s; warm phase 0; ledger byte-stable' \
			% (c['counters']['compiles'], ', '.join(compiled)))"; \
	rc=$$?; rm -rf $$D; exit $$rc

# Memory-ledger smoke: the bundled 10-fleet gateway trace (drift-only,
# so steady-state serving is pure warm path) replayed with the memory
# ledger on (serve --memory-out). The contract: (1) at least one
# registered entry point got a static memory model from the AOT XLA
# memory_analysis pass (graceful None is for backends that don't report
# — the CPU backend does); (2) the leak gate was marked at the warm
# boundary and live-array bytes stayed FLAT through the steady-state
# warm phase (the zero-leak invariant the bench gates absolutely as
# memory_leak_bytes); (3) no watermark sample failed; (4) the dumped
# ledger JSONL round-trips byte-stably and `solver memory` renders
# byte-identical reports on repeated replays of the same dump.
.PHONY: smoke-memory
smoke-memory: lint-strict
	@D=$$(mktemp -d) && \
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli serve \
		--trace tests/traces/gateway_smoke_10f.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--workers 2 --k-candidates 8,10 --quiet \
		--memory-out $$D/memory.jsonl --metrics-out $$D/m.json \
		> /dev/null && \
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli memory \
		--load $$D/memory.jsonl --check > $$D/report1.txt && \
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli memory \
		--load $$D/memory.jsonl --check > $$D/report2.txt && \
	cmp -s $$D/report1.txt $$D/report2.txt && \
	JAX_PLATFORMS=cpu python -c "import json; \
		m = json.load(open('$$D/m.json'))['memory']; \
		leak = m['leak']; \
		assert leak is not None, 'leak gate never marked'; \
		assert leak['flat'], ('warm phase grew live-array bytes', leak); \
		assert m['watermarks']['samples'] > 0, 'no watermark samples'; \
		assert m['watermarks']['sample_errors'] == 0, 'watermark sample failed'; \
		analyzed = [n for n, e in m['entries'].items() if e.get('memory')]; \
		assert analyzed, 'no entry point got a static memory model'; \
		print('smoke-memory OK: %d entry model(s) (%s), leak gate FLAT (%+d B), peak live %.2f MB' \
			% (len(analyzed), ', '.join(analyzed), leak['growth_bytes'], \
			   m['watermarks']['peak_live_bytes'] / 1e6))"; \
	rc=$$?; rm -rf $$D; exit $$rc

# Sharded-mesh smoke: the row-partitioned PDHG engine (ops/meshlp.py) on
# a forced 4-device host mesh, end to end through the solve CLI. Three
# solves of the bundled fixture under the same gap/engine: (1) the plain
# path; (2) --mesh-shards 1, which must be BIT-identical to (1) — the
# shards=1 knob dispatches onto the very same executable, so any
# difference is a threading bug, not numerics; (3) --mesh-shards 4, which
# must certify with the objective inside the optimality-gap envelope of
# (1). The CLI forces the host device count itself before the backend
# initializes (utils.shardcompat), so this runs on any CPU box.
.PHONY: smoke-shard
smoke-shard: lint-strict
	@D=$$(mktemp -d) && \
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli \
		--profile tests/profiles/llama_3_70b/online --backend jax \
		--lp-backend pdhg --mip-gap 1e-4 \
		--save-solution $$D/base.json > /dev/null && \
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli \
		--profile tests/profiles/llama_3_70b/online --backend jax \
		--lp-backend pdhg --mip-gap 1e-4 --mesh-shards 1 \
		--save-solution $$D/one.json > /dev/null && \
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli \
		--profile tests/profiles/llama_3_70b/online --backend jax \
		--lp-backend pdhg --mip-gap 1e-4 --mesh-shards 4 \
		--save-solution $$D/mesh.json > /dev/null && \
	python -c "import json; \
		base = json.load(open('$$D/base.json')); \
		one = json.load(open('$$D/one.json')); \
		mesh = json.load(open('$$D/mesh.json')); \
		assert one['obj_value'] == base['obj_value'], ('shards=1 not bit-stable', one['obj_value'], base['obj_value']); \
		assert (one['k'], one['w'], one['n']) == (base['k'], base['w'], base['n']), 'shards=1 placement drifted'; \
		assert mesh['certified'], 'sharded solve not certified'; \
		assert abs(mesh['obj_value'] - base['obj_value']) <= 2e-4 * abs(base['obj_value']), ('sharded objective outside gap', mesh['obj_value'], base['obj_value']); \
		assert sum(mesh['w']) > 0 and all(0 <= n <= w for w, n in zip(mesh['w'], mesh['n'])), 'invalid sharded placement'; \
		print('smoke-shard OK: shards=1 bit-stable, 4-shard mesh certified at obj %.6f (unsharded %.6f)' \
			% (mesh['obj_value'], base['obj_value']))"; \
	rc=$$?; rm -rf $$D; exit $$rc

.PHONY: smoke-sched
smoke-sched: lint-strict
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli serve \
		--trace tests/traces/scheduler_smoke_20.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--synthetic-fleet 4 --fleet-seed 11 --k-candidates 8,10 \
		--quiet --fail-uncertified

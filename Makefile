# dlint (tools/dlint/) is the stdlib-only correctness gate and runs
# everywhere; ruff stays authoritative for style wherever it is installed.
.PHONY: lint
lint:
	python -m tools.dlint
	@if command -v ruff >/dev/null 2>&1; then ruff check .; fi

# Strict gate for CI and the tier-1 path: any non-baselined finding fails,
# and the baseline itself must be empty or justified (no stale entries, a
# reason on every entry). See README "Static analysis gate".
.PHONY: lint-strict
lint-strict:
	python -m tools.dlint --strict
	@if command -v ruff >/dev/null 2>&1; then ruff check .; fi

.PHONY: format
format:
	ruff format --diff .

.PHONY: test
test: lint-strict
	python -m pytest tests/ -q

.PHONY: bench
bench:
	python bench.py

# Regression gate for the perf dev loop: run the bench and diff every
# headline metric against a committed capture (default: the latest
# BENCH_rNN.json). Exits nonzero on a >20% regression of `value` (cold
# sweep ms) or `warm_tick_ms` (streaming fast path). The gate compares
# ABSOLUTE milliseconds, so the reference must come from the same box —
# when tiny_put_ms (the recorded per-op dispatch floor) differs >1.5x the
# compare prints a not-comparable warning; re-capture a local reference
# (`python bench.py > /tmp/ref.json`) before trusting the verdict. Usage:
#   make bench-compare                      # vs $(AGAINST)
#   make bench-compare AGAINST=BENCH_r04.json
AGAINST ?= BENCH_r05.json
.PHONY: bench-compare
bench-compare:
	python bench.py --against $(AGAINST)

# Scheduler-service smoke: replay the bundled 20-event churn trace through
# the daemon on the CPU platform (no slow tests, no accelerator needed);
# any structural tick missing its optimality certificate fails the target.
# Chained behind lint-strict so the smoke path can't drift from the gate.
.PHONY: smoke-sched
smoke-sched: lint-strict
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli serve \
		--trace tests/traces/scheduler_smoke_20.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--synthetic-fleet 4 --fleet-seed 11 --k-candidates 8,10 \
		--quiet --fail-uncertified

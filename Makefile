.PHONY: lint
lint:
	@command -v ruff >/dev/null 2>&1 && ruff check . || python tools/lint.py

.PHONY: format
format:
	ruff format --diff .

.PHONY: test
test:
	python -m pytest tests/ -q

.PHONY: bench
bench:
	python bench.py

.PHONY: lint
lint:
	@command -v ruff >/dev/null 2>&1 && ruff check . || python tools/lint.py

.PHONY: format
format:
	ruff format --diff .

.PHONY: test
test:
	python -m pytest tests/ -q

.PHONY: bench
bench:
	python bench.py

# Scheduler-service smoke: replay the bundled 20-event churn trace through
# the daemon on the CPU platform (no slow tests, no accelerator needed);
# any structural tick missing its optimality certificate fails the target.
.PHONY: smoke-sched
smoke-sched:
	JAX_PLATFORMS=cpu python -m distilp_tpu.cli.solver_cli serve \
		--trace tests/traces/scheduler_smoke_20.jsonl \
		--profile tests/profiles/llama_3_70b/online \
		--synthetic-fleet 4 --fleet-seed 11 --k-candidates 8,10 \
		--quiet --fail-uncertified
